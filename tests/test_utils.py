"""Config validation, checkpoint/resume, tracing."""

import numpy as np
import pytest

from skyline_tpu.metrics.tracing import Tracer
from skyline_tpu.ops import skyline_np
from skyline_tpu.stream import EngineConfig, SkylineEngine
from skyline_tpu.utils.checkpoint import load_engine, save_engine
from skyline_tpu.utils.config import JobConfig, parse_job_args


def test_job_config_defaults_match_reference():
    # FlinkSkyline.java:62-72 defaults
    cfg = JobConfig()
    assert cfg.parallelism == 4
    assert cfg.algo == "mr-angle"
    assert cfg.input_topic == "input-tuples"
    assert cfg.query_topic == "queries"
    assert cfg.output_topic == "output-skyline"
    assert cfg.domain == 1000.0
    assert cfg.dims == 2
    assert cfg.engine_config().num_partitions == 8


def test_job_config_validation():
    with pytest.raises(ValueError):
        JobConfig(algo="nope")
    with pytest.raises(ValueError):
        JobConfig(parallelism=0)
    with pytest.raises(ValueError):
        JobConfig(domain=-1)


def test_parse_job_args_flags():
    cfg = parse_job_args(["--parallelism", "2", "--algo", "mr-grid",
                          "--dims", "4", "--domain", "500"])
    assert cfg.parallelism == 2 and cfg.algo == "mr-grid"
    assert cfg.dims == 4 and cfg.domain == 500.0


def test_parse_job_args_env_override(monkeypatch):
    monkeypatch.setenv("SKYLINE_DIMS", "6")
    assert parse_job_args([]).dims == 6
    # CLI beats env
    assert parse_job_args(["--dims", "3"]).dims == 3


def test_checkpoint_resume_same_results(rng, tmp_path):
    cfg = EngineConfig(parallelism=2, algo="mr-angle", dims=3, buffer_size=128)
    x = rng.uniform(0, 1000, size=(2000, 3)).astype(np.float32)
    x1, x2 = x[:1200], x[1200:]

    # run A: straight through
    ea = SkylineEngine(cfg)
    ea.process_records(np.arange(1200, dtype=np.int64), x1)
    ea.process_records(np.arange(1200, 2000, dtype=np.int64), x2)
    ea.process_trigger("0,0")
    (ra,) = ea.poll_results()

    # run B: checkpoint mid-stream (with pending rows + a pending query),
    # restore into a fresh engine, continue
    eb = SkylineEngine(cfg)
    eb.process_records(np.arange(1200, dtype=np.int64), x1)
    eb.process_trigger("9,1900")  # deferred: barrier beyond current ids
    assert eb.poll_results() == []
    ckpt = str(tmp_path / "engine.npz")
    save_engine(eb, ckpt)
    restored = load_engine(ckpt)
    assert restored.inflight_queries == 1
    restored.process_records(np.arange(1200, 2000, dtype=np.int64), x2)
    results = restored.poll_results()
    assert len(results) == 1  # the deferred query fires after resume
    assert results[0]["query_id"] == "9"
    assert results[0]["skyline_size"] == skyline_np(x).shape[0]
    assert ra["skyline_size"] == results[0]["skyline_size"]


def test_checkpoint_preserves_counters(rng, tmp_path):
    cfg = EngineConfig(parallelism=1, algo="mr-dim", dims=2, buffer_size=64)
    e = SkylineEngine(cfg)
    e.process_records(np.arange(500, dtype=np.int64),
                      rng.uniform(0, 1000, size=(500, 2)).astype(np.float32))
    ckpt = str(tmp_path / "c.npz")
    save_engine(e, ckpt)
    r = load_engine(ckpt)
    assert r.records_in == 500
    assert [p.max_seen_id for p in r.partitions] == [p.max_seen_id for p in e.partitions]
    assert [p.records_seen for p in r.partitions] == [p.records_seen for p in e.partitions]


def test_tracer_phases():
    tr = Tracer()
    with tr.phase("a"):
        with tr.phase("b"):
            pass
    with tr.phase("a"):
        pass
    rep = tr.report()
    assert rep["a"]["count"] == 2
    assert rep["b"]["count"] == 1
    assert rep["a"]["total_ms"] >= 0


def test_checkpoint_preserves_all_config_flags(rng, tmp_path):
    """Watchdog/prefilter flags must survive restore — a reverted
    query_timeout_ms=0 would resurrect the reference's wait-forever latch."""
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    cfg = EngineConfig(parallelism=2, algo="mr-grid", dims=2,
                       domain_max=100.0, query_timeout_ms=1234.5,
                       grid_prefilter=True)
    eng = SkylineEngine(cfg)
    x = rng.uniform(0, 100, size=(100, 2)).astype(np.float32)
    eng.process_records(np.arange(100), x)
    path = str(tmp_path / "ck.npz")
    save_engine(eng, path)
    restored = load_engine(path)
    assert restored.config == cfg


def test_checkpoint_lazy_policy_roundtrip(rng, tmp_path):
    # a lazy-policy engine (unflushed window accumulated on host) must
    # restore with its policy AND its pending rows intact, and answer the
    # same query identically
    from skyline_tpu.ops import skyline_np
    from skyline_tpu.stream import EngineConfig, SkylineEngine
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    cfg = EngineConfig(parallelism=2, algo="mr-angle", dims=3,
                       domain_max=1000.0, flush_policy="lazy",
                       emit_skyline_points=True)
    eng = SkylineEngine(cfg)
    x = rng.uniform(0, 1000, size=(4000, 3)).astype(np.float32)
    ids = np.arange(4000, dtype=np.int64)
    eng.process_records(ids[:2500], x[:2500])
    path = str(tmp_path / "lazy.npz")
    save_engine(eng, path)
    restored = load_engine(path)
    assert restored.config.flush_policy == "lazy"
    assert restored.pset.flush_policy == "lazy"
    restored.process_records(ids[2500:], x[2500:])
    restored.process_trigger("0,0")
    (r,) = restored.poll_results()
    oracle = skyline_np(x)
    assert r["skyline_size"] == oracle.shape[0]
    got = np.asarray(r["skyline_points"])
    assert set(map(tuple, got.round(3))) == set(map(tuple, oracle.round(3)))


def test_backend_probe_file_cache(monkeypatch, tmp_path):
    """Probe verdicts persist across processes (ISSUE 5 satellite): a
    successful verdict is served from the artifacts/ file within TTL with
    provenance stamped into probe_total_s, failures are never persisted,
    and TTL=0 disables the file cache entirely."""
    from skyline_tpu.utils import backend_probe as bp

    cache = str(tmp_path / "probe_cache.json")
    monkeypatch.setattr(bp, "_cache_path", lambda: cache)
    monkeypatch.setenv("SKYLINE_PROBE_CACHE_TTL_S", "3600")
    monkeypatch.setattr(bp, "_VERDICT", None)
    good = {"backend": "cpu", "n_devices": 1, "attempts": 1,
            "errors": [], "probe_s": 1.2, "probe_total_s": 1.3}
    bp._store_file_verdict(good)
    # fresh "process" (module global reset): served from the file, no
    # subprocess — provenance moves the probed wall time aside
    v = bp.probe_backend(0.001)
    assert v["cached"] and v["cache_source"] == "file"
    assert v["probe_total_s"] == 0.0
    assert v["probe_total_s_probed"] == 1.3
    assert v["backend"] == "cpu" and "cache_age_s" in v
    # second call in the same process: process cache, provenance intact
    v2 = bp.probe_backend(0.001)
    assert v2["cache_source"] == "process"
    assert v2["probe_total_s_probed"] == 1.3
    # failure verdicts must not outlive their process
    import os

    os.remove(cache)
    bp._store_file_verdict({"backend": None, "n_devices": 0})
    assert not os.path.exists(cache)
    # expired entries are ignored
    import json as _json

    bp._store_file_verdict(good)
    with open(cache) as f:
        rec = _json.load(f)
    rec["ts"] -= 10_000_000
    with open(cache, "w") as f:
        _json.dump(rec, f)
    assert bp._load_file_verdict() is None
    # TTL=0 disables store and load
    monkeypatch.setenv("SKYLINE_PROBE_CACHE_TTL_S", "0")
    os.remove(cache)
    bp._store_file_verdict(good)
    assert not os.path.exists(cache)
