"""Worker-surface features reachable from the shell: stats endpoint, meshed
worker, watchdog + engine knobs via JobConfig/CLI flags."""

import json
import urllib.request

import numpy as np
import pytest

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.ops import skyline_np
from skyline_tpu.stream import EngineConfig
from skyline_tpu.utils.config import JobConfig, parse_job_args
from skyline_tpu.workload.generators import anti_correlated


def test_jobconfig_cli_covers_engine_knobs():
    cfg = parse_job_args(
        [
            "--query-timeout-ms", "2500",
            "--grid-prefilter",
            "--initial-capacity", "4096",
            "--flush-policy", "lazy",
            "--stats-port", "0",
        ]
    )
    ec = cfg.engine_config()
    assert ec.query_timeout_ms == 2500
    assert ec.grid_prefilter is True
    assert ec.initial_capacity == 4096
    assert ec.flush_policy == "lazy"


def test_jobconfig_validation():
    with pytest.raises(ValueError):
        JobConfig(flush_policy="bogus")
    # lazy + mesh is a supported combination (shard_map SFS rounds)
    JobConfig(mesh=2, flush_policy="lazy")
    with pytest.raises(ValueError):
        JobConfig(mesh=3, parallelism=4)  # 8 partitions % 3 != 0
    with pytest.raises(ValueError):
        JobConfig(query_timeout_ms=-1)


def test_stats_endpoint_serves_live_counters(rng):
    bus = MemoryBus()
    worker = SkylineWorker(
        bus,
        EngineConfig(parallelism=2, algo="mr-angle", dims=2,
                     domain_max=10000.0, buffer_size=256),
        stats_port=0,  # pick a free port
    )
    import urllib.error
    try:
        port = worker.stats_server.port
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
            assert json.load(r) == {"ok": True}
        x = anti_correlated(rng, 2000, 2, 0, 10000)
        bus.produce_many(
            "input-tuples",
            [format_tuple_line(i, row) for i, row in enumerate(x)],
        )
        bus.produce("queries", format_trigger(0, 0))
        while worker.step() > 0:
            pass
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/stats") as r:
            stats = json.load(r)
        assert stats["records_in"] == 2000
        assert stats["results_emitted"] == 1
        assert stats["inflight_queries"] == 0
        assert len(stats["partitions"]["records_seen"]) == 4
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
        assert exc.value.code == 404
    finally:
        worker.close()


def test_meshed_worker_end_to_end(rng):
    # --mesh N from the shell: partition state sharded over N virtual
    # devices, full transport->result plane, exact result
    cfg = parse_job_args(["--parallelism", "2", "--dims", "2",
                          "--domain", "10000", "--mesh", "2"])
    mesh = cfg.build_mesh()
    assert mesh is not None and mesh.devices.size == 2
    bus = MemoryBus()
    worker = SkylineWorker(bus, cfg.engine_config(), mesh=mesh)
    x = anti_correlated(rng, 3000, 2, 0, 10000)
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(i, row) for i, row in enumerate(x)],
    )
    bus.produce("queries", format_trigger(0, 0))
    while worker.step() > 0:
        pass
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["skyline_size"] == skyline_np(x).shape[0]


def test_watchdog_reachable_from_cli(rng):
    # --query-timeout-ms wires through to partial-result finalization
    cfg = parse_job_args(["--parallelism", "1", "--dims", "2",
                          "--query-timeout-ms", "1"])
    bus = MemoryBus()
    worker = SkylineWorker(bus, cfg.engine_config())
    bus.produce_many("input-tuples", ["0,5.0,5.0"])
    # barrier at id 10 never clears on a silent stream
    bus.produce("queries", format_trigger(7, 10))
    while worker.step() > 0:
        pass
    import time

    time.sleep(0.05)  # let the 1 ms timeout lapse
    worker.step()
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["partial"] is True
    assert result["missing_partitions"]


def test_trigger_pending_drain_is_bounded(rng):
    """A producer that keeps the data topic non-empty must not starve a
    pending trigger: the drain stops at max_drain_polls and the trigger is
    applied against what was ingested (regression for the unbounded
    while-lines loop)."""
    import numpy as np

    class FirehoseBus(MemoryBus):
        """MemoryBus whose data consumer refills the topic on every poll,
        emulating a sustained producer outrunning the worker."""

        def consumer(self, topic, from_beginning=True):
            inner = super().consumer(topic, from_beginning)
            if topic != "input-tuples":
                return inner
            bus, counter = self, [0]

            class Refilling:
                def poll(self, max_records):
                    out = inner.poll(max_records)
                    i = counter[0]
                    counter[0] += 3
                    for k in range(3):  # one tuple per message, like P1
                        bus.produce(
                            "input-tuples",
                            f"{i + k},{float(i + k)},{float(i + k)}",
                        )
                    return out

            return Refilling()

    bus = FirehoseBus()
    cfg = EngineConfig(parallelism=2, algo="mr-dim", dims=2, domain_max=1e9)
    worker = SkylineWorker(bus, cfg, max_drain_polls=5)
    bus.produce("input-tuples", "0,1.0,2.0")
    bus.produce("queries", "7,0")
    worker.step()  # must terminate (bounded) and answer the trigger
    out = bus.consumer("output-skyline", from_beginning=True).poll(10)
    assert len(out) == 1 and '"query_id": "7"' in out[0]


def test_drain_bound_warns_with_trigger_pending(rng, capsys):
    """Hitting the drain bound while a trigger is pending warns on stderr
    (an immediate trigger then answers against a truncated ingest)."""
    class Endless:
        def __init__(self):
            self.i = 0

        def poll(self, max_records):
            i, self.i = self.i, self.i + 1
            return [f"{i},{float(i)},{float(i)}"]

    class EndlessBus(MemoryBus):
        def consumer(self, topic, from_beginning=True):
            if topic == "input-tuples":
                return Endless()
            return super().consumer(topic, from_beginning)

    bus = EndlessBus()
    cfg = EngineConfig(parallelism=1, algo="mr-dim", dims=2, domain_max=1e9)
    worker = SkylineWorker(bus, cfg, max_drain_polls=3)
    bus.produce("queries", "9,0")
    worker.step()
    err = capsys.readouterr().err
    assert "drain bound hit" in err
    assert "--max-drain-polls" in err


def test_max_drain_polls_cli_flag():
    cfg = parse_job_args(["--max-drain-polls", "7"])
    assert cfg.max_drain_polls == 7
    with pytest.raises(ValueError):
        JobConfig(max_drain_polls=0)


def test_stats_dashboard_served():
    """The root URL serves the human dashboard (Flink-Web-UI role); /stats
    stays JSON."""
    import json
    import urllib.request

    from skyline_tpu.metrics.httpstats import StatsServer

    srv = StatsServer(lambda: {"records_in": 7, "partitions": {}}, 0)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/"
        ) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/html")
            assert "tpu-skyline worker" in body and "/stats" in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/stats"
        ) as r:
            assert json.load(r)["records_in"] == 7
    finally:
        srv.close()


def test_arrays_plane_oversized_batch_chunks_to_max_records(rng):
    """A transport whose poll_arrays returns far more rows than
    ``max_records`` (one 16 MB fetch can carry ~100x the micro-batch size)
    must still feed the engine in max_records chunks — the carry buffer
    preserves step()'s documented ingest granularity and order."""
    import numpy as np

    from skyline_tpu.stream import EngineConfig

    class ArraysBus(MemoryBus):
        """MemoryBus whose data consumer serves one big array batch."""

        def __init__(self, ids, values):
            super().__init__()
            self._ids, self._values = ids, values
            self._served = False
            outer = self

            class _ArraysConsumer:
                def poll(self, max_records=65536):
                    return []

                def poll_arrays(self, dims):
                    if outer._served:
                        return (
                            np.empty(0, np.int64),
                            np.empty((0, dims), np.float32),
                            0,
                        )
                    outer._served = True
                    return outer._ids, outer._values, 3  # 3 fake drops

            self._arrays_consumer = _ArraysConsumer()

        def consumer(self, topic, from_beginning=True):
            if topic == "input-tuples":
                return self._arrays_consumer
            return super().consumer(topic, from_beginning)

    n = 1000
    values = rng.uniform(0, 100, (n, 2)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    bus = ArraysBus(ids, values)
    w = SkylineWorker(
        bus, EngineConfig(parallelism=2, algo="mr-dim", dims=2, domain_max=100.0)
    )
    seen = []
    orig = w.engine.process_records

    def spy(ids_, vals_, now_ms=None, event_ms=None):
        seen.append(ids_.shape[0])
        return orig(ids_, vals_, now_ms=now_ms, event_ms=event_ms)

    w.engine.process_records = spy
    got = w.step(max_records=256)
    assert got == 256 + 3  # first micro-batch + the reported drops
    while w.step(max_records=256):
        pass
    assert seen == [256, 256, 256, 232]
    assert w.engine.dropped == 3
    assert w.engine.records_in == n
    # stream order preserved across the carry
    bus.produce("queries", "1,900")
    w.step()
    out = bus.consumer("output-skyline", from_beginning=True).poll(5)
    assert len(out) == 1
