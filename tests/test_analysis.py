"""The static-analysis gate (RUNBOOK 2h): registry, three passes, fixtures.

Two layers:

1. The real tree is clean — all three passes produce zero findings, the
   registry's defaults agree with JobConfig's field defaults, and
   docs/KNOBS.md has not drifted. These ARE the CI gate (scripts/lint.sh
   runs the module; this runs it in-process).
2. Seeded-violation fixtures — each rule demonstrably fires, at the right
   file:line, on a minimal reproduction written to tmp_path. A lint whose
   rules are never seen firing is one refactor away from passing on
   everything.
"""

from __future__ import annotations

import ast
import os
import textwrap

import pytest

from skyline_tpu.analysis import knob_lint, lock_lint
from skyline_tpu.analysis.__main__ import default_roots, main, repo_root
from skyline_tpu.analysis.registry import (
    KNOBS,
    Knob,
    env_bool,
    env_float,
    env_int,
    env_str,
    knob,
    knob_doc_markdown,
    parse_bool,
)

REPO = repo_root()


# -------------------------------------------------------------------------
# layer 1: the real tree is clean
# -------------------------------------------------------------------------


def test_knob_lint_clean_on_tree():
    findings = knob_lint.run(default_roots(REPO), REPO)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_lock_lint_clean_on_tree():
    findings = lock_lint.run(default_roots(REPO), REPO)
    assert findings == [], "\n".join(f.format() for f in findings)


def test_lock_lint_guards_actually_collected():
    # zero findings must mean "mutations are locked", not "annotations were
    # never parsed": the seeded classes expose their guard maps
    expected = {
        "skyline_tpu/serve/snapshot.py": ("SnapshotStore", "_latest"),
        "skyline_tpu/serve/deltas.py": ("DeltaRing", "_ring"),
        "skyline_tpu/telemetry/histogram.py": ("Histogram", "_counts"),
        "skyline_tpu/telemetry/spans.py": ("SpanRecorder", "_ring"),
        "skyline_tpu/metrics/collector.py": ("Counters", "_counts"),
    }
    for rel, (cls_name, attr) in expected.items():
        path = os.path.join(REPO, rel)
        src = open(path, encoding="utf-8").read()
        tree = ast.parse(src)
        cls = next(
            n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef) and n.name == cls_name
        )
        guards = lock_lint._collect_guards(cls, src.splitlines())
        assert attr in guards, (rel, cls_name, guards)


def test_jaxpr_audit_clean_on_tree():
    from skyline_tpu.analysis import jaxpr_audit

    findings, summary = jaxpr_audit.run()
    assert findings == [], "\n".join(f.format() for f in findings)
    assert summary["dims"] == [2, 4, 8]
    # the full matrix: 3 mask dims + 1 sorted-SFS containment leg + 2
    # device-cascade mp legs + 1 device-cascade containment leg + 2 dims
    # x 2 mp x 2 ops + 2 dims x 2 summary kernels + 3 cache-stability legs
    assert summary["configs_traced"] == 22


def test_cli_exits_zero_on_tree():
    assert main(["--pass", "knobs,locks"]) == 0


def test_registry_defaults_match_jobconfig():
    # flag-backed knobs carry job_field; their registry default must equal
    # the JobConfig field default or the doc table lies about behavior
    from skyline_tpu.utils.config import JobConfig

    cfg = JobConfig()
    flagged = [k for k in KNOBS if k.job_field]
    assert len(flagged) >= 30  # the whole flag surface is declared
    for k in flagged:
        assert hasattr(cfg, k.job_field), k.name
        assert getattr(cfg, k.job_field) == k.default, (
            f"{k.name}: registry default {k.default!r} != "
            f"JobConfig.{k.job_field} default {getattr(cfg, k.job_field)!r}"
        )


def test_knob_doc_covers_registry_and_is_current():
    doc = knob_doc_markdown()
    for k in KNOBS:
        assert f"`{k.name}`" in doc, k.name
    on_disk = os.path.join(REPO, "docs", "KNOBS.md")
    assert os.path.isfile(on_disk), "run python -m skyline_tpu.analysis --knob-doc"
    assert open(on_disk, encoding="utf-8").read() == doc, (
        "docs/KNOBS.md drifted — regenerate with --knob-doc"
    )
    assert main(["--check-doc"]) == 0


def test_undeclared_knob_raises_at_runtime():
    with pytest.raises(LookupError):
        knob("SKYLINE_NO_SUCH_KNOB")
    with pytest.raises(LookupError):
        env_str("SKYLINE_NO_SUCH_KNOB")


# -------------------------------------------------------------------------
# layer 2: seeded violations — every rule fires, right file:line
# -------------------------------------------------------------------------


def _lint_fixture(tmp_path, source: str):
    p = tmp_path / "fixture.py"
    p.write_text(textwrap.dedent(source))
    findings, reads = knob_lint.lint_paths([str(p)], str(tmp_path))
    return findings, reads


def test_raw_env_read_fires(tmp_path):
    findings, _ = _lint_fixture(
        tmp_path,
        """\
        import os

        def f():
            a = os.environ.get("SKYLINE_MERGE_CACHE", "1")
            b = os.environ["SKYLINE_MERGE_TREE"]
            c = os.getenv("SKYLINE_STAGE_DEPTH")
            d = "SKYLINE_MERGE_PRUNE" in os.environ
            return a, b, c, d
        """,
    )
    raw = [f for f in findings if f.rule == "raw-env-read"]
    assert sorted(f.line for f in raw) == [4, 5, 6, 7]
    assert all(f.file == "fixture.py" and f.severity == "error" for f in raw)


def test_raw_env_write_and_passthrough_allowed(tmp_path):
    findings, _ = _lint_fixture(
        tmp_path,
        """\
        import os

        def f():
            os.environ["SKYLINE_MERGE_CACHE"] = "0"
            os.environ.pop("SKYLINE_MERGE_CACHE", None)
            env = dict(os.environ)
            for k, v in os.environ.items():
                env[k] = v
            return env
        """,
    )
    assert [f for f in findings if f.rule == "raw-env-read"] == []


def test_suppression_comment_allows_raw_read(tmp_path):
    findings, _ = _lint_fixture(
        tmp_path,
        """\
        import os

        def snapshot(keys):
            return {k: os.environ.get(k) for k in keys}  # lint: allow-raw-env
        """,
    )
    assert [f for f in findings if f.rule == "raw-env-read"] == []


def test_undeclared_knob_fires(tmp_path):
    findings, reads = _lint_fixture(
        tmp_path,
        """\
        from skyline_tpu.analysis.registry import env_bool

        def f():
            return env_bool("SKYLINE_TOTALLY_UNDECLARED", False)
        """,
    )
    hits = [f for f in findings if f.rule == "undeclared-knob"]
    assert len(hits) == 1 and hits[0].line == 4
    assert "SKYLINE_TOTALLY_UNDECLARED" in hits[0].message
    assert "SKYLINE_TOTALLY_UNDECLARED" in reads


def test_dynamic_knob_name_fires(tmp_path):
    findings, _ = _lint_fixture(
        tmp_path,
        """\
        from skyline_tpu.analysis.registry import env_int

        def f(name):
            return env_int(f"SKYLINE_{name}", 0)
        """,
    )
    hits = [f for f in findings if f.rule == "dynamic-knob-name"]
    assert len(hits) == 1 and hits[0].line == 4


def test_dead_knob_fires():
    # simulate a tree that reads every knob except one declared gate
    all_names = {k.name for k in KNOBS}
    victim = "SKYLINE_MERGE_PRUNE"
    hits = knob_lint.dead_knobs(all_names - {victim})
    assert len(hits) == 1
    assert hits[0].rule == "dead-knob" and victim in hits[0].message
    # external knobs (JAX_PLATFORMS, XLA_FLAGS) are exempt from deadness
    externals = {k.name for k in KNOBS if k.external}
    assert externals
    assert knob_lint.dead_knobs(all_names - externals) == []


def test_bool_compare_fires(tmp_path):
    findings, _ = _lint_fixture(
        tmp_path,
        """\
        import os

        from skyline_tpu.analysis.registry import env_str

        def f():
            return env_str("SKYLINE_ALGO", "") != "0"

        def g():
            return os.environ.get("SKYLINE_MERGE_CACHE") == "true"
        """,
    )
    hits = [f for f in findings if f.rule == "bool-compare"]
    assert sorted(f.line for f in hits) == [6, 9]
    # comparing against a non-truthiness literal is fine (backend names)
    findings2, _ = _lint_fixture(
        tmp_path,
        """\
        from skyline_tpu.analysis.registry import env_str

        def f():
            return env_str("JAX_PLATFORMS", "") == "cpu"
        """,
    )
    assert [f for f in findings2 if f.rule == "bool-compare"] == []


def test_unguarded_mutation_fires(tmp_path):
    p = tmp_path / "locky.py"
    p.write_text(textwrap.dedent(
        """\
        import threading


        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock
                self.version = 0  # guarded-by: self._lock

            def good(self, x):
                with self._lock:
                    self._items.append(x)
                    self.version += 1

            def bad_call(self, x):
                self._items.append(x)

            def bad_assign(self):
                self.version = 7

            def wrong_lock(self, other, x):
                with other:
                    self._items.append(x)

            def suppressed(self):
                self.version += 1  # unguarded-ok: single-writer int bump
        """
    ))
    findings = lock_lint.lint_file(str(p), "locky.py")
    hits = {f.line: f for f in findings}
    assert set(hits) == {16, 19, 23}, findings
    assert all(f.rule == "unguarded-mutation" for f in findings)
    assert "Store._items" in hits[16].message and "self._lock" in hits[16].message
    assert "Store.version" in hits[19].message


def test_nested_function_does_not_inherit_lock(tmp_path):
    p = tmp_path / "nested.py"
    p.write_text(textwrap.dedent(
        """\
        import threading


        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: self._lock

            def leaky(self):
                with self._lock:
                    def later():
                        self._items.append(1)
                    return later
        """
    ))
    findings = lock_lint.lint_file(str(p), "nested.py")
    assert [f.line for f in findings] == [12]


def test_jaxpr_f64_and_callback_fixtures():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from skyline_tpu.analysis.jaxpr_audit import audit_closed_jaxpr

    jax.config.update("jax_enable_x64", True)
    try:
        closed = jax.make_jaxpr(
            lambda x: x * jnp.asarray(np.float64(2.0), dtype=jnp.float64)
        )(jnp.ones((4,), jnp.float32))
    finally:
        jax.config.update("jax_enable_x64", False)
    hits = audit_closed_jaxpr(closed, "seeded-f64")
    assert any(f.rule == "jaxpr-f64" for f in hits), hits

    def with_callback(x):
        return jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    closed2 = jax.make_jaxpr(with_callback)(jnp.ones((4,), jnp.float32))
    hits2 = audit_closed_jaxpr(closed2, "seeded-callback")
    assert any(f.rule == "jaxpr-host-callback" for f in hits2), hits2


def test_jaxpr_bf16_gate_fixture():
    import jax
    import jax.numpy as jnp

    from skyline_tpu.analysis.jaxpr_audit import audit_closed_jaxpr

    exact = jax.make_jaxpr(lambda x: x + 1.0)(jnp.ones((4,), jnp.float32))
    mixed = jax.make_jaxpr(
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32)
    )(jnp.ones((4,), jnp.float32))
    # bf16 leaked into an exact trace
    assert any(
        f.rule == "jaxpr-bf16-gate"
        for f in audit_closed_jaxpr(mixed, "leak", expect_bf16=False)
    )
    # mp trace with no bf16 at all
    assert any(
        f.rule == "jaxpr-bf16-gate"
        for f in audit_closed_jaxpr(exact, "missing", expect_bf16=True)
    )
    # and the two correct pairings are silent
    assert audit_closed_jaxpr(exact, "ok", expect_bf16=False) == []
    assert audit_closed_jaxpr(mixed, "ok", expect_bf16=True) == []


# -------------------------------------------------------------------------
# the unified boolean parser (satellite 5)
# -------------------------------------------------------------------------

_GATES = (
    ("SKYLINE_MERGE_CACHE", True),
    ("SKYLINE_MERGE_TREE", True),
    ("SKYLINE_RANK_CASCADE", False),
    ("SKYLINE_FLUSH_PREFILTER", True),
)


def test_parse_bool_contract():
    for raw in ("0", "false", "no", "off", "False", " OFF "):
        assert parse_bool(raw, True) is False, raw
    for raw in ("1", "true", "yes", "on", "TRUE", " On "):
        assert parse_bool(raw, False) is True, raw
    for raw in (None, "", "  ", "banana"):
        assert parse_bool(raw, True) is True, raw
        assert parse_bool(raw, False) is False, raw


def test_falsy_spellings_identical_everywhere(monkeypatch):
    """'0', 'false', and (for default-False knobs) unset agree at every
    consumer: the registry accessor, the dispatch gates, and JobConfig."""
    from skyline_tpu.ops import dispatch
    from skyline_tpu.utils.config import parse_job_args

    gate_fns = {
        "SKYLINE_MERGE_CACHE": dispatch.merge_cache_enabled,
        "SKYLINE_MERGE_TREE": dispatch.merge_tree_enabled,
        "SKYLINE_RANK_CASCADE": dispatch.rank_cascade,
        "SKYLINE_FLUSH_PREFILTER": dispatch.flush_prefilter_enabled,
    }
    for name, default in _GATES:
        fn = gate_fns[name]
        for raw in ("0", "false", "no", "off"):
            monkeypatch.setenv(name, raw)
            assert env_bool(name, default) is False, (name, raw)
            assert fn() is False, (name, raw)
        for raw in ("1", "true", "yes", "on"):
            monkeypatch.setenv(name, raw)
            assert env_bool(name, default) is True, (name, raw)
            assert fn() is True, (name, raw)
        monkeypatch.delenv(name, raising=False)
        assert env_bool(name, default) is default, name
        assert fn() is default, name
    # the flag surface: '0' and 'false' both disable; unset means default
    for raw in ("0", "false"):
        monkeypatch.setenv("SKYLINE_EMIT_PER_SLIDE", raw)
        assert parse_job_args([]).emit_per_slide is False, raw
    monkeypatch.setenv("SKYLINE_EMIT_PER_SLIDE", "true")
    assert parse_job_args([]).emit_per_slide is True
    monkeypatch.delenv("SKYLINE_EMIT_PER_SLIDE", raising=False)
    assert parse_job_args([]).emit_per_slide is False


def test_mixed_precision_tristate(monkeypatch):
    from skyline_tpu.ops.dispatch import mixed_precision_enabled, on_tpu

    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", "0")
    assert mixed_precision_enabled() is False
    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", "false")
    assert mixed_precision_enabled() is False
    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", "1")
    assert mixed_precision_enabled() is True
    monkeypatch.delenv("SKYLINE_MIXED_PRECISION", raising=False)
    assert mixed_precision_enabled() is on_tpu()


def test_numeric_parse_errors_fall_back_with_warning(monkeypatch):
    import warnings

    from skyline_tpu.analysis import registry

    monkeypatch.setenv("SKYLINE_STAGE_DEPTH", "not-an-int")
    monkeypatch.setattr(registry, "_warned", set())
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert env_int("SKYLINE_STAGE_DEPTH", 1) == 1
    assert any("SKYLINE_STAGE_DEPTH" in str(x.message) for x in w)
    monkeypatch.setenv("SKYLINE_DELTA_CUTOFF", "nope")
    monkeypatch.setattr(registry, "_warned", set())
    assert env_float("SKYLINE_DELTA_CUTOFF", 0.75) == 0.75


def test_registry_declarations_are_well_formed():
    names = [k.name for k in KNOBS]
    assert len(names) == len(set(names))
    for k in KNOBS:
        assert isinstance(k, Knob)
        assert k.type in ("bool", "int", "float", "str", "enum"), k.name
        assert k.description, k.name
        assert k.applies_to, k.name
        if k.type == "enum":
            assert k.choices, k.name
