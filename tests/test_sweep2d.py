"""Sort-sweep low-dimensional skylines (ops/sweep2d.py): property tests
against the O(n^2) oracle and the scan kernel, heavy-tie and duplicate
semantics (ServiceTuple.java:67-77 parity — duplicates all survive), the
partitioned variant's segment isolation, and the d=1 degenerate encoding
used by the flush path."""

import jax.numpy as jnp
import numpy as np
import pytest

from skyline_tpu.ops.block_skyline import skyline_mask_scan
from skyline_tpu.ops.dominance import skyline_np
from skyline_tpu.ops.sweep2d import (
    partitioned_sweep2,
    skyline_mask_sweep,
)
from tests.conftest import assert_same_set


def _case(rng, kind, n):
    if kind == "uniform":
        return rng.uniform(0, 1000, (n, 2)).astype(np.float32)
    if kind == "ties":
        return rng.integers(0, 8, (n, 2)).astype(np.float32)
    if kind == "anti":
        b = rng.uniform(0, 1000, (n, 1))
        return np.abs((1000 - b) + rng.normal(0, 60, (n, 2))).astype(
            np.float32
        )
    return np.tile(rng.uniform(0, 9, (1, 2)).astype(np.float32), (n, 1))


@pytest.mark.parametrize("kind", ["uniform", "ties", "anti", "dups"])
def test_sweep_matches_scan_and_oracle(kind, rng):
    for n in (1, 7, 512, 2500):
        x = _case(rng, kind, n)
        valid = rng.random(n) < 0.85
        if not valid.any():
            valid[0] = True
        got = np.asarray(
            skyline_mask_sweep(jnp.asarray(x), jnp.asarray(valid))
        )
        ref = np.asarray(
            skyline_mask_scan(
                jnp.asarray(np.where(valid[:, None], x, np.inf)),
                jnp.asarray(valid),
            )
        )
        assert (got == ref).all()
        want = skyline_np(x[valid].astype(np.float64))
        assert int(got.sum()) == want.shape[0]
        assert_same_set(x[got], want)


def test_sweep_d1_all_minima_survive(rng):
    x = rng.integers(0, 20, (800, 1)).astype(np.float32)
    valid = rng.random(800) < 0.9
    valid[:2] = True
    got = np.asarray(skyline_mask_sweep(jnp.asarray(x), jnp.asarray(valid)))
    mn = x[valid].min()
    assert (got == (valid & (x[:, 0] == mn))).all()


def test_sweep_invalid_only_and_pads():
    x = np.full((16, 2), np.inf, dtype=np.float32)
    valid = np.zeros(16, dtype=bool)
    got = np.asarray(skyline_mask_sweep(jnp.asarray(x), jnp.asarray(valid)))
    assert not got.any()


def test_partitioned_sweep_matches_per_partition_oracle(rng):
    for trial in range(8):
        P = int(rng.integers(1, 9))
        n = int(rng.integers(1, 4000))
        x = rng.integers(0, 40, (n, 2)).astype(np.float32)
        pids = rng.integers(0, P, n).astype(np.int32)
        valid = rng.random(n) < 0.9
        sky, counts = partitioned_sweep2(
            jnp.asarray(x), jnp.asarray(pids), jnp.asarray(valid), P, n + 1
        )
        sky, counts = np.asarray(sky), np.asarray(counts)
        for p in range(P):
            want = skyline_np(x[valid & (pids == p)].astype(np.float64))
            assert counts[p] == want.shape[0]
            assert_same_set(sky[p][: counts[p]], want)
            assert np.isinf(sky[p][counts[p] :]).all()


def test_partitioned_sweep_cap_clips_not_corrupts(rng):
    """Survivors past cap are dropped and counts clipped — never scattered
    out of bounds into another partition."""
    P, n = 3, 300
    # all points mutually non-dominating within partition: anti-chain line
    x = np.stack(
        [np.arange(n, dtype=np.float32), -np.arange(n, dtype=np.float32)],
        axis=1,
    )
    pids = (np.arange(n) % P).astype(np.int32)
    valid = np.ones(n, dtype=bool)
    cap = 8
    sky, counts = partitioned_sweep2(
        jnp.asarray(x), jnp.asarray(pids), jnp.asarray(valid), P, cap
    )
    sky, counts = np.asarray(sky), np.asarray(counts)
    assert (counts == cap).all()
    for p in range(P):
        assert np.isfinite(sky[p]).all()
        assert (sky[p][:, 0] % P == p).all()  # rows stayed in their partition
