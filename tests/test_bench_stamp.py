"""bench.py artifact provenance: the rank_cascade stamp in the bench JSON
must track the dispatcher's single source of truth
(``ops.dispatch.rank_cascade``), not a re-read of SKYLINE_RANK_CASCADE with
a duplicated default that can silently drift (ADVICE.md round 5)."""

import bench

from skyline_tpu.ops import dispatch


def test_rank_cascade_stamp_tracks_dispatch(monkeypatch):
    monkeypatch.delenv("SKYLINE_RANK_CASCADE", raising=False)
    assert bench.rank_cascade_stamp() is dispatch.rank_cascade() is False
    monkeypatch.setenv("SKYLINE_RANK_CASCADE", "1")
    assert bench.rank_cascade_stamp() is dispatch.rank_cascade() is True
    monkeypatch.setenv("SKYLINE_RANK_CASCADE", "0")
    assert bench.rank_cascade_stamp() is dispatch.rank_cascade() is False
