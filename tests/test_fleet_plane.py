"""Fleet observability plane (ISSUE 13): per-chip health & skew telemetry.

Unit math for the imbalance index + edge-triggered flight entries, the
labeled ``skyline_chip_*{chip=...}`` Prometheus families, the sharded
engine feeding the plane end-to-end, the ``/fleet`` join on the stats
HTTP surface, and the byte-identity law with the plane on or off.
"""

import json
import urllib.request

import numpy as np
import pytest

from conftest import gen_points
from skyline_tpu.distributed import ShardedEngine
from skyline_tpu.stream import EngineConfig
from skyline_tpu.telemetry import Telemetry
from skyline_tpu.telemetry.fleet import FleetStats, fleet_doc
from skyline_tpu.telemetry.profiler import FlightRecorder


# --------------------------------------------------------------------------
# FleetStats unit math
# --------------------------------------------------------------------------


def test_imbalance_index_math():
    f = FleetStats(2, imbalance_threshold=2.0)
    f.note_ingest(0, 300)
    f.note_ingest(1, 100)
    doc = f.note_merge_done()
    # max load / mean load: 300 / 200
    assert doc["imbalance_index"] == pytest.approx(1.5)
    assert doc["loads"] == [300, 100]
    assert f.doc()["merges"] == 1


def test_imbalance_flight_entry_is_edge_triggered():
    flight = FlightRecorder(16)
    f = FleetStats(2, flight=flight, imbalance_threshold=1.2)
    f.note_ingest(0, 900)
    f.note_ingest(1, 100)
    # index 1.8 > 1.2 on every merge, but the excursion logs ONCE
    for _ in range(3):
        f.note_merge_done()
    notes = [e for e in flight.doc()["entries"]
             if e["kind"] == "fleet.imbalance"]
    assert len(notes) == 1
    assert f.doc()["imbalance_events"] == 1
    # balance restored, then skewed again: a second excursion, second note
    f.note_ingest(1, 800)
    f.note_merge_done()
    f.note_ingest(0, 4000)
    f.note_merge_done()
    notes = [e for e in flight.doc()["entries"]
             if e["kind"] == "fleet.imbalance"]
    assert len(notes) == 2


def test_level2_prune_vs_survive_accounting():
    f = FleetStats(3)
    f.note_level2(0, False, 0)  # root chip: survives, ships nothing
    f.note_level2(1, False, 128)
    f.note_level2(2, True, 0)
    doc = f.doc()
    per = {pc["chip"]: pc for pc in doc["per_chip"]}
    assert per[0]["survived"] == 1 and per[0]["interconnect_rows"] == 0
    assert per[1]["interconnect_rows"] == 128
    assert per[2]["pruned"] == 1
    assert doc["interconnect_rows_total"] == 128


def test_labeled_prometheus_families():
    hub = Telemetry()
    f = FleetStats(2)
    f.note_ingest(0, 10)
    f.note_ingest(1, 30)
    f.note_merge_done()
    hub.fleet = f
    body = hub.render_prometheus()
    assert '# TYPE skyline_chip_ingest_rows_total counter' in body
    assert 'skyline_chip_ingest_rows_total{chip="0"} 10' in body
    assert 'skyline_chip_ingest_rows_total{chip="1"} 30' in body
    assert '# TYPE skyline_fleet_imbalance_index gauge' in body
    assert 'skyline_chip_skyline_size{chip="0"}' in body


def test_unlabeled_exposition_unchanged_without_fleet():
    a = Telemetry().render_prometheus()
    hub = Telemetry()
    hub.fleet = FleetStats(2)
    b = hub.render_prometheus()
    # attaching the plane only ADDS families; every pre-existing line is
    # byte-identical
    assert set(a.splitlines()) <= set(b.splitlines())


# --------------------------------------------------------------------------
# sharded engine end-to-end
# --------------------------------------------------------------------------


def _run_sharded(x, chips=2, telemetry=None):
    cfg = EngineConfig(parallelism=2, dims=x.shape[1], domain_max=1.0,
                       buffer_size=64, emit_skyline_points=True)
    eng = ShardedEngine(cfg, chips=chips, telemetry=telemetry)
    ids = np.arange(x.shape[0], dtype=np.int64)
    for i in range(0, x.shape[0], 200):
        eng.process_records(ids[i : i + 200], x[i : i + 200])
    eng.process_trigger("q,0")
    (res,) = eng.poll_results()
    return eng, res


def test_sharded_engine_populates_fleet_plane(rng):
    hub = Telemetry()
    eng, _res = _run_sharded(gen_points(rng, 600, 2, "uniform"),
                             telemetry=hub)
    assert hub.fleet is not None
    doc = hub.fleet.doc()
    assert doc["chips"] == 2
    assert doc["merges"] >= 1
    assert all(pc["ingest_rows"] > 0 for pc in doc["per_chip"])
    assert all(pc["flush_rows"] > 0 for pc in doc["per_chip"])
    # every unpruned level-1 merge stamps a local skyline size
    assert any(pc["skyline_size"] > 0 for pc in doc["per_chip"])
    # the root chip's skyline is already device-resident: 0 crossed rows
    per = {pc["chip"]: pc for pc in doc["per_chip"]}
    assert per[0]["interconnect_rows"] == 0
    assert doc["imbalance_index"] >= 1.0
    # the imbalance block rides the EXPLAIN chips attribution
    plan = hub.explain.latest()
    assert plan["chips"]["imbalance"]["imbalance_index"] >= 1.0
    # sharded_stats carries the doc for /stats readers
    assert eng.stats()["sharded"]["fleet"]["chips"] == 2
    # per-chip level-1 child spans + the level-2 interconnect span
    names = [s["name"] for s in hub.spans.snapshot()]
    assert "chip_merge" in names and "cross_chip_merge" in names


def test_fleet_doc_join_and_http_surface(rng):
    from skyline_tpu.metrics.httpstats import StatsServer

    hub = Telemetry()
    eng, _res = _run_sharded(gen_points(rng, 500, 2, "correlated"),
                             telemetry=hub)
    doc = fleet_doc(hub, eng.stats())
    assert doc["enabled"] is True
    assert doc["chips"] == 2
    assert doc["last_query"] is not None
    assert doc["last_query"]["chips"]["chips"] == 2
    srv = StatsServer(eng.stats, port=0, telemetry=hub)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/fleet", timeout=10
        ) as r:
            got = json.load(r)
        assert got["enabled"] is True and got["chips"] == 2
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10
        ) as r:
            body = r.read().decode()
        assert 'skyline_chip_ingest_rows_total{chip="1"}' in body
    finally:
        srv.close()


def test_fleet_doc_reports_disabled_on_flat_worker():
    doc = fleet_doc(Telemetry(), {})
    assert doc == {"enabled": False, "health": None,
                   "freshness_wm_ms": None, "last_query": None}


def test_serve_surface_fleet_route(rng):
    from skyline_tpu.serve import SkylineServer, SnapshotStore

    hub = Telemetry()
    eng, _res = _run_sharded(gen_points(rng, 400, 2, "uniform"),
                             telemetry=hub)
    srv = SkylineServer(SnapshotStore(), stats_cb=eng.stats, port=0,
                        telemetry=hub)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/fleet", timeout=10
        ) as r:
            got = json.load(r)
        assert got["enabled"] is True and got["chips"] == 2
    finally:
        srv.close()


@pytest.mark.parametrize("kind", ["uniform", "anti_correlated"])
def test_byte_identity_with_plane_on_and_off(rng, monkeypatch, kind):
    x = gen_points(rng, 700, 4, kind)
    monkeypatch.setenv("SKYLINE_FLEET", "0")
    eng_off, off = _run_sharded(x, telemetry=Telemetry())
    assert eng_off.telemetry.fleet is None
    monkeypatch.setenv("SKYLINE_FLEET", "1")
    _eng_on, on = _run_sharded(x, telemetry=Telemetry())
    assert on["skyline_size"] == off["skyline_size"]
    np.testing.assert_array_equal(
        np.asarray(on["skyline_points"], dtype=np.float32),
        np.asarray(off["skyline_points"], dtype=np.float32),
    )
