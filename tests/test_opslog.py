"""Ops plane (ISSUE 17): durable cross-process ops journal, fleet-wide
clusterview aggregation, and replication/fencing telemetry.

Acceptance bars:

- the journal survives torn tails and CRC-corrupt frames exactly like
  the WAL: every record before the first bad frame is returned, the
  tear is counted, nothing raises;
- two writers appending into the same journal directory keep their seqs
  monotone per writer and the reader merges the timeline by wall time;
- the clusterview flags an injected split-brain (two live primaries; a
  writer below the fleet's max fence) as named findings and stays quiet
  on a healthy grid;
- replica lag renders as LABELED Prometheus families
  (``skyline_replica_lag_versions{replica=...}``) and the unlabeled
  exposition stays byte-identical when no labeled provider registers;
- ``GET /ops`` and ``GET /cluster/overview`` answer on the stats
  surface, probe-friendly when the plane is off.
"""

from __future__ import annotations

import json
import os
import urllib.request

import numpy as np
import pytest

from skyline_tpu.telemetry import Telemetry
from skyline_tpu.telemetry import opslog as opsmod
from skyline_tpu.telemetry.clusterview import (
    hist_quantile,
    overview_from_members,
    parse_prometheus,
)
from skyline_tpu.telemetry.opslog import (
    OpsLog,
    list_journals,
    ops_doc,
    read_ops,
)

from conftest import parse_prometheus_text


# ---------------------------------------------------------------------------
# journal durability
# ---------------------------------------------------------------------------


def test_roundtrip_fields_and_since_seq(tmp_path):
    d = str(tmp_path)
    ops = OpsLog(d, process_id="worker-a-1", fsync="off")
    try:
        rec = ops.record(
            "fence_raised", epoch=3, fence=3, trace_id="t-1", cut_seq=7
        )
        assert rec is not None and rec["seq"] == 1
        ops.record("promoted", epoch=3, holder="r0")
        ops.record("demoted", epoch=2)
    finally:
        ops.close()
    doc = read_ops(d)
    assert doc["enabled"] and doc["writers"] == 1 and doc["torn"] == 0
    assert doc["total"] == 3
    first = doc["records"][0]
    assert first["type"] == "fence_raised"
    assert first["proc"] == "worker-a-1"
    assert first["epoch"] == first["fence"] == 3
    assert first["trace_id"] == "t-1" and first["cut_seq"] == 7
    assert first["t_ms"] > 0
    # since_seq is a per-writer high-water mark: only the unseen suffix
    tail = read_ops(d, since_seq=1)
    assert [r["seq"] for r in tail["records"]] == [2, 3]
    assert read_ops(d, since_seq=3)["total"] == 0
    # limit keeps the newest N after filtering
    assert [r["seq"] for r in read_ops(d, limit=1)["records"]] == [3]


def test_torn_tail_returns_prefix(tmp_path):
    d = str(tmp_path)
    ops = OpsLog(d, fsync="off")
    for i in range(5):
        ops.record("lease_acquired", epoch=i)
    ops.close()
    (path,) = list_journals(d)
    # an os.write cut mid-frame leaves a frame prefix: simulate the crash
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\x99")  # header + truncated payload
    doc = read_ops(d)
    assert doc["torn"] == 1
    assert [r["seq"] for r in doc["records"]] == [1, 2, 3, 4, 5]


def test_crc_corruption_keeps_trustworthy_prefix(tmp_path):
    d = str(tmp_path)
    ops = OpsLog(d, fsync="off")
    for i in range(6):
        ops.record("lease_acquired", epoch=i)
    ops.close()
    (path,) = list_journals(d)
    with open(path, "rb") as f:
        data = bytearray(f.read())
    # flip one payload byte two-thirds in: full-length garbage, CRC must
    # catch it and the reader must stop there without raising
    data[len(data) * 2 // 3] ^= 0xFF
    with open(path, "wb") as f:
        f.write(data)
    doc = read_ops(d)
    assert doc["torn"] == 1
    seqs = [r["seq"] for r in doc["records"]]
    assert 0 < len(seqs) < 6
    assert seqs == sorted(seqs)


def test_bad_magic_is_torn_not_fatal(tmp_path):
    d = str(tmp_path)
    ops = OpsLog(d, fsync="off")
    ops.record("promoted", epoch=1)
    ops.close()
    (path,) = list_journals(d)
    with open(path, "r+b") as f:
        f.write(b"NOPE")
    doc = read_ops(d)
    assert doc["torn"] == 1 and doc["total"] == 0


def test_size_cap_drops_and_counts_never_raises(tmp_path):
    d = str(tmp_path)
    ops = OpsLog(d, fsync="off", max_bytes=256)
    wrote = dropped = 0
    for i in range(50):
        if ops.record("lease_acquired", epoch=i) is None:
            dropped += 1
        else:
            wrote += 1
    assert dropped > 0 and wrote > 0
    st = ops.stats()
    assert st["dropped"] == dropped and st["appends"] == wrote
    ops.close()
    assert ops.record("promoted") is None  # closed: counted, not raised
    assert read_ops(d)["total"] == wrote


# ---------------------------------------------------------------------------
# cross-process interleaving
# ---------------------------------------------------------------------------


def test_two_writers_merge_by_wall_time(tmp_path, monkeypatch):
    d = str(tmp_path)
    tick = {"now": 1000.0}

    def clock():
        tick["now"] += 1.0
        return tick["now"] / 1000.0  # time.time() is in seconds

    monkeypatch.setattr(opsmod.time, "time", clock)
    a = OpsLog(d, process_id="worker-a-1", fsync="off")
    b = OpsLog(d, process_id="worker-b-2", fsync="off")
    try:
        # strict interleave in wall time: a, b, a, b, a, b
        for i in range(3):
            a.record("lease_acquired", epoch=i)
            b.record("replica_bootstrap", replica=f"r{i}")
    finally:
        a.close()
        b.close()
    doc = read_ops(d)
    assert doc["writers"] == 2 and doc["torn"] == 0 and doc["total"] == 6
    recs = doc["records"]
    # merged timeline reads in wall-time order across processes
    assert [r["t_ms"] for r in recs] == sorted(r["t_ms"] for r in recs)
    assert [r["proc"][7] for r in recs] == list("ababab")
    # per-writer seq stays monotone through the merge
    for proc in ("worker-a-1", "worker-b-2"):
        seqs = [r["seq"] for r in recs if r["proc"] == proc]
        assert seqs == sorted(seqs) == [1, 2, 3]
    # since_seq filters per writer, not globally
    tail = read_ops(d, since_seq=2)
    assert sorted((r["proc"], r["seq"]) for r in tail["records"]) == [
        ("worker-a-1", 3),
        ("worker-b-2", 3),
    ]


def test_fresh_file_per_incarnation(tmp_path):
    d = str(tmp_path)
    first = OpsLog(d, fsync="off")
    first.record("lease_acquired", epoch=1)
    first.close()
    second = OpsLog(d, fsync="off")
    second.record("lease_acquired", epoch=2)
    second.close()
    # a restart never appends into a file a crashed incarnation may have
    # left torn — one journal file per incarnation
    assert len(list_journals(d)) == 2
    assert read_ops(d)["total"] == 2


def test_ops_doc_probe_friendly():
    assert ops_doc(None) == {"ok": True, "enabled": False}
    assert ops_doc("/nonexistent-skyline-opslog-dir")["enabled"] is False


def test_cli_print_and_diff(tmp_path, capsys):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    for d, epochs in ((d1, (1, 2)), (d2, (1,))):
        ops = OpsLog(d, process_id="worker-cli-9", fsync="off")
        for e in epochs:
            ops.record("fence_raised", epoch=e, fence=e)
        ops.close()
    assert opsmod.main([d1, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["total"] == 2
    assert opsmod.main([d1, d2]) == 0
    out = capsys.readouterr().out
    assert "fence_raised" in out
    assert opsmod.main(["/nonexistent-skyline-opslog-dir", "--json"]) == 2


# ---------------------------------------------------------------------------
# clusterview: healthy grid quiet, injected split-brain flagged
# ---------------------------------------------------------------------------


def _member(url, role, epoch, fence, head, ok=True):
    return {
        "url": url,
        "ok": ok,
        "healthz": {"ok": ok, "role": role},
        "cluster": {
            "enabled": True,
            "role": role,
            "lease": {"epoch": epoch},
            "fence": fence,
        },
        "metrics": {"skyline_snapshot_store_head_version": float(head)},
        "ops": {"enabled": True, "records": [], "writers": 1},
    }


def test_clusterview_quiet_on_healthy_grid():
    doc = overview_from_members(
        [
            _member("http://a", "primary", 4, 4, 30),
            _member("http://b", "replica", 4, 4, 28),
            _member("http://c", "replica", 4, 4, 30),
        ],
        now_ms=1.0,
    )
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["fleet"]["live"] == 3 and doc["fleet"]["primaries"] == 1
    assert doc["fleet"]["primary_head_version"] == 30
    lags = {
        m["url"]: m["replication_lag_versions"]
        for m in doc["members"]
        if m["role"] != "primary"
    }
    assert lags["http://b"] == 2 and lags["http://c"] == 0


def test_clusterview_flags_injected_split_brain():
    doc = overview_from_members(
        [
            _member("http://a", "primary", 3, 5, 30),  # below fleet fence
            _member("http://b", "primary", 5, 5, 30),
        ],
        now_ms=1.0,
    )
    assert doc["ok"] is False
    names = sorted(f["name"] for f in doc["findings"])
    assert names == ["multiple_primaries", "primary_below_fence"]
    assert all(f["severity"] == "critical" for f in doc["findings"])
    # a DEAD duplicate primary is not a split-brain: liveness gates it
    quiet = overview_from_members(
        [
            _member("http://a", "primary", 5, 5, 30),
            _member("http://b", "primary", 4, 5, 30, ok=False),
        ],
        now_ms=1.0,
    )
    assert [f["name"] for f in quiet["findings"]] == []


def test_split_brain_evidence_from_real_fence(tmp_path):
    """The stale-fence story end to end on real components: a fenced
    writer's zombie append is rejected AND journaled, and the clusterview
    built from the real lease-plane state names the finding."""
    from skyline_tpu.cluster import (
        FencedWalWriter,
        LeasePlane,
        WalFencedError,
    )

    d = str(tmp_path)
    ops = OpsLog(d, process_id="worker-zombie-1", fsync="off")
    plane = LeasePlane(d)
    lease = plane.acquire("primary-0", ttl_ms=60_000.0)
    writer = FencedWalWriter(
        d, lease.epoch, plane=plane, fsync="off", opslog=ops
    )
    try:
        new_epoch = plane.raise_fence(lease.epoch + 1)  # fence the zombie
        with pytest.raises(WalFencedError):
            writer.append({"type": "delta", "probe": True})
    finally:
        writer.close()
        ops.close()
    recs = read_ops(d)["records"]
    zombies = [r for r in recs if r["type"] == "zombie_append_rejected"]
    assert zombies and zombies[0]["fence"] == new_epoch
    assert zombies[0]["epoch"] == lease.epoch
    # the view over that real state: old-epoch writer still claiming
    # primary under the raised fence is a named critical finding
    doc = overview_from_members(
        [_member("http://a", "primary", lease.epoch, new_epoch, 1)],
        now_ms=1.0,
    )
    assert [f["name"] for f in doc["findings"]] == ["primary_below_fence"]


def test_parse_prometheus_and_hist_quantile():
    text = (
        "# TYPE skyline_x_total counter\n"
        "skyline_x_total 3\n"
        'skyline_replica_lag_ms{replica="r0"} 12.5\n'
        'skyline_tail_ms_bucket{le="1"} 0\n'
        'skyline_tail_ms_bucket{le="10"} 8\n'
        'skyline_tail_ms_bucket{le="+Inf"} 10\n'
    )
    samples = parse_prometheus(text)
    assert samples["skyline_x_total"] == 3.0
    assert samples['skyline_replica_lag_ms{replica="r0"}'] == 12.5
    q = hist_quantile(samples, "skyline_tail_ms", 0.5)
    assert q is not None and 1.0 <= q <= 10.0


# ---------------------------------------------------------------------------
# replication telemetry: labeled families, unlabeled byte-identity
# ---------------------------------------------------------------------------


def test_unlabeled_exposition_byte_identical_without_providers():
    def build():
        tel = Telemetry()
        tel.inc("queries")
        tel.histogram("merge_ms", unit="ms").observe(3.0)
        return tel

    base = build().render_prometheus()
    quiet = build()
    quiet.replication.append(lambda: ({}, {}))  # plane on, nothing to say
    assert quiet.render_prometheus() == base


def test_labeled_replica_families_render_and_survive_bad_provider():
    tel = Telemetry()
    tel.inc("queries")

    def provider():
        return (
            {"replica_rebootstraps": [((("replica", "r0"),), 2.0)]},
            {
                "replica_lag_ms": [
                    ((("replica", "r0"),), 12.5),
                    ((("replica", "r1"),), 3.0),
                ],
                "replica_lag_versions": [((("replica", "r0"),), 4.0)],
            },
        )

    def dying():
        raise RuntimeError("replica died mid-scrape")

    tel.replication.extend([provider, dying])
    text = tel.render_prometheus()
    assert 'skyline_replica_lag_ms{replica="r0"} 12.5' in text
    assert 'skyline_replica_lag_ms{replica="r1"} 3' in text
    assert 'skyline_replica_lag_versions{replica="r0"} 4' in text
    assert 'skyline_replica_rebootstraps_total{replica="r0"} 2' in text
    # exposition stays parseable with labeled + unlabeled families mixed
    series = parse_prometheus_text(text)
    assert len(series["skyline_replica_lag_ms"]) == 2


def test_real_replica_exports_labeled_lag_on_shared_hub(tmp_path):
    from skyline_tpu.resilience.wal import WalWriter
    from skyline_tpu.serve import SnapshotStore, delta_wal_record
    from skyline_tpu.serve.replica import SkylineReplica

    d = str(tmp_path)
    hub = Telemetry()
    writer = WalWriter(d, fsync="off")
    store = SnapshotStore()

    def shadow(prev, snap):
        writer.append(delta_wal_record(prev, snap))
        writer.flush(force=True)

    store.on_publish(shadow)
    rng = np.random.default_rng(5)
    for _ in range(4):
        store.publish(rng.random((64, 3), dtype=np.float32))
    replica = SkylineReplica(
        d,
        replica_id="rT",
        poll_interval_s=0.001,
        telemetry=hub,
        primary_head_cb=lambda: store.head_version,
    )
    try:
        assert replica.wait_for_version(store.head_version, timeout_s=30.0)
        text = hub.render_prometheus()
        series = parse_prometheus_text(text)
        by_label = {
            tuple(sorted(lbl.items())): v
            for lbl, v in series["skyline_replica_head_version"]
        }
        assert by_label[(("replica", "rT"),)] == float(store.head_version)
        assert (("replica", "rT"),) in {
            tuple(sorted(lbl.items())): v
            for lbl, v in series["skyline_replica_lag_versions"]
        }
        lag = {
            tuple(sorted(lbl.items())): v
            for lbl, v in series["skyline_replica_lag_versions"]
        }[(("replica", "rT"),)]
        assert lag == 0.0  # converged
        assert "skyline_replica_records_applied_total" in series
    finally:
        replica.close()
        writer.close()
    # closing deregisters: a dead replica stops contributing series
    assert "skyline_replica_head_version" not in parse_prometheus_text(
        hub.render_prometheus()
    )


# ---------------------------------------------------------------------------
# HTTP surface: /ops and /cluster/overview
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.status, json.loads(r.read().decode())


def test_stats_server_serves_ops_and_overview(tmp_path):
    from skyline_tpu.metrics.httpstats import StatsServer

    d = str(tmp_path)
    hub = Telemetry()
    srv = StatsServer(lambda: {"ok": True}, port=0, telemetry=hub)
    try:
        # plane off: probe-friendly, not a 404
        code, doc = _get(srv.port, "/ops")
        assert code == 200 and doc == {"ok": True, "enabled": False}
        ops = OpsLog(d, process_id="worker-http-1", fsync="off")
        ops.record("promoted", epoch=2, holder="r0")
        ops.record("demoted", epoch=1)
        ops.flush(force=True)
        hub.opslog = ops
        code, doc = _get(srv.port, "/ops")
        assert code == 200 and doc["total"] == 2
        code, doc = _get(srv.port, "/ops?since_seq=1&limit=5")
        assert [r["seq"] for r in doc["records"]] == [2]
        # clusterview off: probe-friendly too
        code, doc = _get(srv.port, "/cluster/overview")
        assert code == 200 and doc["enabled"] is False
        ops.close()
    finally:
        srv.close()
