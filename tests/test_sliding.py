"""Sliding-window continuous skyline: exactness vs oracle, eviction."""

import numpy as np
import pytest

from skyline_tpu.ops import skyline_np
from skyline_tpu.stream.sliding import SlidingSkyline

from conftest import assert_same_set


def test_rejects_misaligned_slide():
    with pytest.raises(ValueError):
        SlidingSkyline(window_size=100, slide=33, dims=2)


def test_sliding_matches_oracle_every_slide(rng):
    W, S, d = 600, 200, 3
    n = 2000
    x = rng.uniform(0, 1000, size=(n, d)).astype(np.float32)
    sw = SlidingSkyline(W, S, d)
    results = []
    for chunk in np.array_split(x, 23):  # ragged batches crossing slides
        results.extend(sw.push(chunk.astype(np.float32)))
    assert len(results) == n // S
    for r in results:
        end = r["window_end"]
        lo = max(0, end + 1 - W)
        expect = skyline_np(x[lo : end + 1])
        assert_same_set(r["skyline"], expect)
        assert r["window_filled"] == (end + 1 >= W)


def test_eviction_resurrects_shadowed_points(rng):
    # a dominated point must REAPPEAR in the skyline once its dominator
    # slides out of the window — the case unbounded streaming can't express
    d = 2
    sw = SlidingSkyline(window_size=4, slide=2, dims=d)
    dominator = np.array([[1.0, 1.0], [900.0, 900.0]], dtype=np.float32)
    shadowed = np.array([[5.0, 5.0], [800.0, 800.0]], dtype=np.float32)
    filler = np.array([[700.0, 600.0], [600.0, 700.0]], dtype=np.float32)
    r1 = sw.push(dominator)  # window: dominator bucket
    r2 = sw.push(shadowed)   # window: dominator+shadowed -> (1,1) wins
    assert not any((r2[0]["skyline"] == [5.0, 5.0]).all(axis=1))
    r3 = sw.push(filler)     # dominator bucket evicted -> (5,5) resurfaces
    assert any((r3[0]["skyline"] == [5.0, 5.0]).all(axis=1))


def test_current_skyline_includes_pending(rng):
    sw = SlidingSkyline(window_size=100, slide=50, dims=2)
    sw.push(np.array([[10.0, 10.0]], dtype=np.float32))  # pending only
    cur = sw.current_skyline
    assert cur.shape == (1, 2)
