"""Randomized cross-configuration consistency: for random streams, chunk
splits, and interleaved queries, every (flush policy × mesh × partitioner)
combination must produce the oracle skyline of the records ingested before
each trigger — the strongest form of the merge-law / device-count-invariance
properties (SURVEY.md §4), checked jointly instead of per-feature.
"""

import numpy as np
import pytest

from skyline_tpu.ops.dominance import skyline_np
from skyline_tpu.parallel.mesh import make_mesh
from skyline_tpu.stream import EngineConfig, SkylineEngine
from conftest import assert_same_set


def run_fuzz_scenario(seed, max_n: int = 3000, min_n: int = 800):
    """One cross-config consistency scenario; ``max_n``/``min_n`` bound the
    stream so the bounded tier (tests/test_soak.py) stays fast while the
    soak tier runs the full-size version. Defaults reproduce the round-3
    vetted draws exactly (n in [800, 3000))."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(min_n, max_n))
    d = int(rng.integers(2, 5))
    dist = rng.choice(["uniform", "anti"])
    if dist == "uniform":
        x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    else:
        base = rng.uniform(0, 1000, (n, 1))
        x = np.abs(
            (1000 - base) + rng.normal(0, 60, (n, d))
        ).astype(np.float32)
    ids = np.arange(n)
    # two trigger points inside the stream + one at the end
    cut1, cut2 = sorted(rng.integers(1, n, size=2).tolist())
    oracle_1 = skyline_np(x[:cut1])
    oracle_2 = skyline_np(x[:cut2])
    oracle_end = skyline_np(x)

    algo = str(rng.choice(["mr-dim", "mr-grid", "mr-angle"]))
    combos = [
        ("incremental", None, "host"),
        ("lazy", None, "host"),
        ("lazy", None, "device"),
        ("overlap", None, "device"),
        ("incremental", make_mesh(4), "host"),
        ("lazy", make_mesh(4), "host"),
    ]
    for policy, mesh, ingest in combos:
        cfg = EngineConfig(
            parallelism=4, algo=algo, dims=d, domain_max=1000.0,
            buffer_size=int(rng.integers(64, 512)),
            flush_policy=policy, emit_skyline_points=True,
            ingest=ingest, overlap_rows=int(rng.integers(128, 1024)),
        )
        eng = SkylineEngine(cfg, mesh=mesh)
        pos = 0
        results = []
        for stop in (cut1, cut2, n):
            while pos < stop:
                step = int(rng.integers(1, 700))
                end = min(pos + step, stop)
                eng.process_records(ids[pos:end], x[pos:end])
                pos = end
            eng.process_trigger(f"{len(results)},0")
            results.extend(eng.poll_results())
        assert len(results) == 3, (policy, mesh, len(results))
        for r, want in zip(results, (oracle_1, oracle_2, oracle_end)):
            assert r["skyline_size"] == want.shape[0], (
                policy, bool(mesh), algo, r["skyline_size"], want.shape[0],
            )
            assert_same_set(r["skyline_points"], want)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_policies_meshes_partitioners(seed):
    run_fuzz_scenario(seed)
