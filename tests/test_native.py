"""Native CSV parser: build, parity with the Python parser, speed sanity."""

import numpy as np
import pytest

from skyline_tpu import native
from skyline_tpu.bridge.wire import format_tuple_line


def _python_parse(lines, dims):
    # the semantics-defining fallback, bypassing the native fast path
    import skyline_tpu.bridge.wire as wire

    ids, rows, dropped = [], [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        parts = line.split(",")
        if len(parts) != dims + 1:
            dropped += 1
            continue
        try:
            rid = int(parts[0])
            vals = [float(p) for p in parts[1:]]
        except ValueError:
            dropped += 1
            continue
        if not all(np.isfinite(v) for v in vals):
            dropped += 1
            continue
        ids.append(rid)
        rows.append(vals)
    return (
        np.asarray(ids, dtype=np.int64),
        np.asarray(rows, dtype=np.float32).reshape(len(rows), dims),
        dropped,
    )


needs_native = pytest.mark.skipif(
    native.get_lib() is None, reason="native build unavailable"
)


@needs_native
def test_native_matches_python_on_clean_lines(rng):
    lines = [
        format_tuple_line(i, row)
        for i, row in enumerate(rng.uniform(0, 10000, size=(500, 4)))
    ]
    got = native.parse_tuples_native(("\n".join(lines)).encode(), 4, len(lines))
    ids, vals, dropped = got
    pids, pvals, pdropped = _python_parse(lines, 4)
    assert dropped == pdropped == 0
    np.testing.assert_array_equal(ids, pids)
    np.testing.assert_allclose(vals, pvals, rtol=1e-6)


@needs_native
def test_native_matches_python_on_dirty_lines():
    lines = [
        "1,10,20",
        "garbage",
        "2,10",            # wrong arity
        "3,x,20",          # non-numeric
        "4,nan,20",        # non-finite
        "5,inf,20",
        "6,30,40",
        "7,30,40,50",      # too many fields
        "-8,1.5,2.75",     # negative id, decimals
        "9,1e2,2.5e-1",    # exponents
        "",                # blank (skipped entirely by both)
    ]
    n_ids, n_vals, n_drop = native.parse_tuples_native(
        ("\n".join(lines)).encode(), 2, len(lines)
    )
    p_ids, p_vals, p_drop = _python_parse(lines, 2)
    np.testing.assert_array_equal(n_ids, p_ids)
    np.testing.assert_allclose(n_vals, p_vals, rtol=1e-6)
    assert n_drop == p_drop


@needs_native
def test_native_integer_fast_path_exact():
    lines = ["0,12345,67890", "1,0,9999999"]
    ids, vals, _ = native.parse_tuples_native(("\n".join(lines)).encode(), 2, 2)
    np.testing.assert_array_equal(vals, [[12345.0, 67890.0], [0.0, 9999999.0]])


@needs_native
def test_native_crlf_tolerated():
    ids, vals, drop = native.parse_tuples_native(b"1,2,3\r\n2,4,5\r\n", 2, 2)
    assert list(ids) == [1, 2]
    assert drop == 0


def test_wire_uses_native_when_available(rng):
    # end-to-end through the public wire function (whichever path is active)
    from skyline_tpu.bridge.wire import parse_tuple_lines

    lines = [format_tuple_line(i, r) for i, r in enumerate(rng.uniform(0, 100, size=(50, 3)))]
    lines.insert(10, "bogus,line")
    ids, vals, dropped = parse_tuple_lines(lines, 3)
    assert len(ids) == 50 and dropped == 1
