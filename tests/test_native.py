"""Native CSV parser: build, parity with the Python parser, speed sanity."""

import numpy as np
import pytest

from skyline_tpu import native
from skyline_tpu.bridge.wire import format_tuple_line


def _python_parse(lines, dims):
    # the semantics-defining fallback, bypassing the native fast path
    import skyline_tpu.bridge.wire as wire

    ids, rows, dropped = [], [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        parts = line.split(",")
        if len(parts) != dims + 1:
            dropped += 1
            continue
        try:
            rid = int(parts[0])
            vals = [float(p) for p in parts[1:]]
        except ValueError:
            dropped += 1
            continue
        if not all(np.isfinite(v) for v in vals):
            dropped += 1
            continue
        ids.append(rid)
        rows.append(vals)
    return (
        np.asarray(ids, dtype=np.int64),
        np.asarray(rows, dtype=np.float32).reshape(len(rows), dims),
        dropped,
    )


needs_native = pytest.mark.skipif(
    native.get_lib() is None, reason="native build unavailable"
)


@needs_native
def test_native_matches_python_on_clean_lines(rng):
    lines = [
        format_tuple_line(i, row)
        for i, row in enumerate(rng.uniform(0, 10000, size=(500, 4)))
    ]
    got = native.parse_tuples_native(("\n".join(lines)).encode(), 4, len(lines))
    ids, vals, dropped = got
    pids, pvals, pdropped = _python_parse(lines, 4)
    assert dropped == pdropped == 0
    np.testing.assert_array_equal(ids, pids)
    np.testing.assert_allclose(vals, pvals, rtol=1e-6)


@needs_native
def test_native_matches_python_on_dirty_lines():
    lines = [
        "1,10,20",
        "garbage",
        "2,10",            # wrong arity
        "3,x,20",          # non-numeric
        "4,nan,20",        # non-finite
        "5,inf,20",
        "6,30,40",
        "7,30,40,50",      # too many fields
        "-8,1.5,2.75",     # negative id, decimals
        "9,1e2,2.5e-1",    # exponents
        "",                # blank (skipped entirely by both)
    ]
    n_ids, n_vals, n_drop = native.parse_tuples_native(
        ("\n".join(lines)).encode(), 2, len(lines)
    )
    p_ids, p_vals, p_drop = _python_parse(lines, 2)
    np.testing.assert_array_equal(n_ids, p_ids)
    np.testing.assert_allclose(n_vals, p_vals, rtol=1e-6)
    assert n_drop == p_drop


@needs_native
def test_native_integer_fast_path_exact():
    lines = ["0,12345,67890", "1,0,9999999"]
    ids, vals, _ = native.parse_tuples_native(("\n".join(lines)).encode(), 2, 2)
    np.testing.assert_array_equal(vals, [[12345.0, 67890.0], [0.0, 9999999.0]])


@needs_native
def test_native_crlf_tolerated():
    ids, vals, drop = native.parse_tuples_native(b"1,2,3\r\n2,4,5\r\n", 2, 2)
    assert list(ids) == [1, 2]
    assert drop == 0


def test_wire_uses_native_when_available(rng):
    # end-to-end through the public wire function (whichever path is active)
    from skyline_tpu.bridge.wire import parse_tuple_lines

    lines = [format_tuple_line(i, r) for i, r in enumerate(rng.uniform(0, 100, size=(50, 3)))]
    lines.insert(10, "bogus,line")
    ids, vals, dropped = parse_tuple_lines(lines, 3)
    assert len(ids) == 50 and dropped == 1


@needs_native
def test_native_crc32c_matches_python(rng):
    from skyline_tpu.bridge.kafkalite.protocol import _crc32c_py

    assert native.crc32c_native(b"") == _crc32c_py(b"")
    # RFC 3720 check vector
    assert native.crc32c_native(b"\x00" * 32) == 0x8A9136AA
    for n in (1, 7, 8, 9, 63, 64, 65, 1000):
        data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        assert native.crc32c_native(data) == _crc32c_py(data), n


@needs_native
def test_native_record_frames_byte_identical(rng):
    """The C record-frame encoder must emit exactly the Python loop's bytes
    for value-only records (incl. empty values and multi-byte varints)."""
    from skyline_tpu.bridge.kafkalite.protocol import _uvarint

    values = [b"", b"x", b"9,5.5", b"v" * 200, b"w" * 20000]
    values += [str(i).encode() * (i % 5) for i in range(300)]
    got = native.encode_records_native(values)
    parts = []
    for i, value in enumerate(values):
        rb = b"\x00\x00" + _uvarint(i << 1)
        rb += b"\x01" + _uvarint(len(value) << 1) + value + b"\x00"
        parts.append(_uvarint(len(rb) << 1) + rb)
    assert got == b"".join(parts)


def test_encode_record_batch_keyed_records_keep_python_path():
    """Keyed records bypass the native value-only fast path and still
    round-trip (decode is format-agnostic)."""
    from skyline_tpu.bridge.kafkalite import protocol as P

    records = [(b"k1", b"v1"), (None, b"v2")]
    blob = P.encode_record_batch(records, base_offset=3)
    assert P.decode_record_batches(blob) == [(3, b"k1", b"v1"), (4, None, b"v2")]


def test_consumer_check_crcs_detects_corruption():
    """check_crcs=True must reject a corrupted batch end-to-end."""
    import pytest

    from skyline_tpu.bridge.kafkalite import protocol as P

    blob = bytearray(P.encode_record_batch([(None, b"payload")]))
    blob[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC32C"):
        P.decode_record_batches(bytes(blob), verify_crc=True)
    # and the default decode path (verify_crc=False callers) still parses
    # the (corrupt) frame rather than crashing
    out = P.decode_record_batches(bytes(blob), verify_crc=False)
    assert len(out) == 1


@needs_native
def test_native_rejects_corrupt_record_length_varint():
    """A corrupt record-length varint (negative or past the batch tail)
    must fail as a clean ValueError from bounds validation done BEFORE
    ``rec_end`` pointer arithmetic (ADVICE.md round 5), never a crash or a
    silent misparse."""
    from skyline_tpu.bridge.kafkalite import protocol as P

    # rec_len = -1 (zigzag 0x01), then bytes that would misparse if the
    # length were trusted
    neg = P._wrap_record_batch(P._uvarint(1) + b"\x00" * 8, 1, 0, 0)
    with pytest.raises(ValueError, match="malformed"):
        native.parse_recordbatches_native(neg, 0, 2)
    # rec_len = 0: a record frame can never be empty
    zero = P._wrap_record_batch(P._uvarint(0) + b"\x00" * 8, 1, 0, 0)
    with pytest.raises(ValueError, match="malformed"):
        native.parse_recordbatches_native(zero, 0, 2)
    # rec_len far beyond the remaining payload
    big = P._wrap_record_batch(
        P._uvarint((1 << 20) << 1) + b"\x00" * 8, 1, 0, 0
    )
    with pytest.raises(ValueError, match="malformed"):
        native.parse_recordbatches_native(big, 0, 2)
    # a well-formed batch through the same wrapper still parses: the
    # rejection above is the corrupt varint, not the hand-rolled framing
    ids, vals, dropped, _ = native.parse_recordbatches_native(
        P.encode_record_batch([(None, b"7,1,2")]), 0, 2
    )
    assert list(ids) == [7] and dropped == 0
