"""Randomized invariant suites, two tiers per scenario:

- bounded tier (default): small streams, a handful of seeds — the same
  invariants (engine cross-config consistency, sliding-vs-oracle, transport
  framing) run on every plain ``pytest`` within ~1 min total.
- soak tier (``SKYLINE_SOAK=1``): the full-size randomized versions,
  condensed from the round-3 soak runs that passed at larger seed counts:
  engine cross-config fuzz x70, sliding vs oracle x40, transport framing x50.
"""

import os

import numpy as np
import pytest

soak = pytest.mark.skipif(
    os.environ.get("SKYLINE_SOAK", "") != "1",
    reason="full-size soak tier is opt-in: set SKYLINE_SOAK=1",
)


# -- scenario bodies (size-parameterized; shared by both tiers) -------------


def _sliding_vs_oracle(seed: int, n_scale: int) -> None:
    from skyline_tpu.ops import skyline_np
    from skyline_tpu.stream.sliding import SlidingSkyline

    rng = np.random.default_rng(seed)
    d = int(rng.integers(2, 6))
    window = int(rng.integers(2, 9)) * 50
    slide = 50
    n = int(rng.integers(6, 6 + n_scale)) * 50
    kind = rng.choice(["uniform", "anti", "dup"])
    if kind == "uniform":
        x = rng.uniform(0, 1000, size=(n, d)).astype(np.float32)
    elif kind == "anti":
        base = rng.uniform(0, 1000, (n, 1))
        x = np.abs((1000 - base) + rng.normal(0, 50, (n, d))).astype(
            np.float32
        )
    else:  # heavy ties/duplicates
        x = rng.uniform(0, 10, size=(n, d)).round().astype(np.float32)
    s = SlidingSkyline(window, slide, d)
    results = []
    for i in range(0, n, 70):  # ragged batches crossing slide edges
        results.extend(s.push(x[i : i + 70]))
    assert len(results) == n // slide
    for r in results:
        end = r["window_end"]
        lo = max(0, end + 1 - window)
        expect = skyline_np(x[lo : end + 1])
        got = np.asarray(r["skyline"], dtype=np.float64)
        assert got.shape[0] == expect.shape[0], (seed, end)
        gs = sorted(map(tuple, got.round(5).tolist()))
        es = sorted(map(tuple, expect.round(5).tolist()))
        assert gs == es, (seed, end)


def _transport_framing(seed: int, max_records: int) -> None:
    from skyline_tpu.bridge.kafkalite.broker import Broker
    from skyline_tpu.bridge.kafkalite.client import (
        KafkaLiteConsumer,
        KafkaLiteProducer,
    )

    rng = np.random.default_rng(seed)
    with Broker() as b:
        prod = KafkaLiteProducer(
            b.address, linger_records=int(rng.integers(1, 5000))
        )
        n = int(rng.integers(1, max_records))
        msgs = [
            f"{i}," + "x" * int(rng.choice([0, 1, 7, 40, 400, 4000]))
            for i in range(n)
        ]
        j = 0
        while j < n:
            if rng.random() < 0.5:
                prod.send("t", msgs[j])
                j += 1
            else:
                k = int(rng.integers(1, 9000))
                prod.send_many("t", msgs[j : j + k])
                j += k
            if rng.random() < 0.2:
                prod.flush()
        prod.flush()
        cons = KafkaLiteConsumer(
            "t", b.address, check_crcs=bool(rng.random() < 0.5)
        )
        got, idle = [], 0
        while len(got) < n and idle < 50:
            batch = cons.poll(int(rng.integers(1, 20000)))
            idle = 0 if batch else idle + 1
            got.extend(batch)
        assert got == msgs, (seed, len(got), n)


# -- bounded tier: runs on every default pytest -----------------------------


@pytest.mark.parametrize("seed", range(10, 13))
def test_engine_cross_config_bounded(seed):
    from test_fuzz_consistency import run_fuzz_scenario

    run_fuzz_scenario(seed, max_n=900, min_n=300)


@pytest.mark.parametrize("seed", range(100, 104))
def test_sliding_vs_oracle_bounded(seed):
    _sliding_vs_oracle(seed, n_scale=4)


@pytest.mark.parametrize("seed", range(3))
def test_transport_framing_bounded(seed):
    _transport_framing(seed, max_records=4000)


# -- soak tier: SKYLINE_SOAK=1 ----------------------------------------------


@soak
@pytest.mark.parametrize("seed", range(10, 22))
def test_soak_engine_cross_config(seed):
    from test_fuzz_consistency import run_fuzz_scenario

    run_fuzz_scenario(seed)


@soak
@pytest.mark.parametrize("seed", range(100, 112))
def test_soak_sliding_vs_oracle(seed):
    _sliding_vs_oracle(seed, n_scale=14)


@soak
@pytest.mark.parametrize("seed", range(12))
def test_soak_transport_framing(seed):
    _transport_framing(seed, max_records=20000)
