"""Hierarchical (host, chip) two-phase skyline: exactness, overflow semantics,
mesh-shape invariance — on the 8-virtual-device CPU platform (conftest)."""

import jax
import numpy as np
import pytest

from skyline_tpu.ops.dominance import skyline_np
from skyline_tpu.parallel.multihost import (
    build_hierarchical_two_phase,
    make_host_chip_mesh,
    shard_rows_2d,
)

from conftest import assert_same_set


def _run(mesh, x, valid, host_cap=None):
    shards = int(mesh.devices.size)
    rows_per_shard = x.shape[0] // shards
    step = build_hierarchical_two_phase(
        mesh, rows_per_shard=rows_per_shard, host_cap=host_cap, local_block=64,
        cross_block=128,
    )
    xs, vs = shard_rows_2d(mesh, x, valid)
    host_keep, global_keep, overflowed = step(xs, vs)
    return (
        np.asarray(host_keep),
        np.asarray(global_keep),
        int(overflowed),
    )


@pytest.mark.parametrize("shape", [(2, 4), (4, 2)])
def test_exact_vs_oracle(rng, shape):
    mesh = make_host_chip_mesh(*shape)
    n, d = 512, 4
    x = rng.uniform(0, 100, size=(n, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    host_keep, global_keep, overflowed = _run(mesh, x, valid)
    assert overflowed == 0
    assert_same_set(x[global_keep], skyline_np(x))
    # host survivors are a superset of global survivors
    assert np.all(host_keep[global_keep])


def test_mesh_shape_invariance(rng):
    n, d = 512, 3
    x = rng.uniform(0, 100, size=(n, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    results = []
    for shape in [(2, 4), (4, 2), (1, 8)]:
        mesh = make_host_chip_mesh(*shape)
        _, global_keep, overflowed = _run(mesh, x, valid)
        assert overflowed == 0
        results.append(x[global_keep])
    assert_same_set(results[0], results[1])
    assert_same_set(results[0], results[2])


def test_padding_rows_excluded(rng):
    mesh = make_host_chip_mesh(2, 4)
    n, d = 256, 3
    x = rng.uniform(0, 100, size=(n, d)).astype(np.float32)
    x[200:] = np.inf
    valid = np.arange(n) < 200
    _, global_keep, overflowed = _run(mesh, x, valid)
    assert overflowed == 0
    assert not global_keep[200:].any()
    assert_same_set(x[global_keep], skyline_np(x[:200]))


def test_overflow_flag_and_superset(rng):
    """An undersized host_cap must raise the overflow flag and may only ADD
    points relative to the true skyline (dominators dropped, never results)."""
    mesh = make_host_chip_mesh(2, 4)
    n, d = 8192, 8
    # anti-correlated-ish: most points survive locally -> host buffers overflow
    base = rng.uniform(0, 100, size=(n, 1)).astype(np.float32)
    x = np.concatenate([base, 100.0 - base + rng.normal(0, 0.01, size=(n, 1))], axis=1)
    x = np.concatenate([x, rng.uniform(0, 100, size=(n, d - 2))], axis=1).astype(
        np.float32
    )
    valid = np.ones(n, dtype=bool)
    _, keep_exact, ov0 = _run(mesh, x, valid)
    assert ov0 == 0
    _, keep_capped, ov1 = _run(mesh, x, valid, host_cap=1024)
    assert ov1 > 0
    # superset: every exact survivor is still kept
    assert np.all(keep_capped[keep_exact])


def test_large_host_cap_multiple_rejected():
    mesh = make_host_chip_mesh(2, 4)
    with pytest.raises(ValueError):
        build_hierarchical_two_phase(mesh, rows_per_shard=64, host_cap=100)


def test_make_mesh_shapes():
    mesh = make_host_chip_mesh(2, 4)
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("host", "chip")
    with pytest.raises(ValueError):
        make_host_chip_mesh(3)  # 8 % 3 != 0
