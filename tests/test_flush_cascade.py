"""Flush dominance cascade (ISSUE 5): quantized grid prefilter + bf16
margin pass must never change a single output byte — property grid over
workload shapes / dims / flush policies / mesh, the edge cases that broke
naive designs (all-dropped batches, NaN/inf rows, bf16-ambiguous ties),
and direct soundness checks of the certified-margin and grid-code
schemes."""

import jax.numpy as jnp
import numpy as np
import pytest

from skyline_tpu.parallel.mesh import make_mesh
from skyline_tpu.stream import EngineConfig, SkylineEngine
from skyline_tpu.stream.batched import PartitionSet
# workload generator shared via conftest.py (satellite of ISSUE 10)
from conftest import assert_same_set, gen_points as _gen


def _run_rounds(pset, rng, x, P, rounds=2):
    """Feed ``x`` in ``rounds`` chunks with a flush after each — round 1's
    flush tail publishes the grid summaries round 2's prefilter uses."""
    pids = rng.integers(0, P, x.shape[0])
    step = -(-x.shape[0] // rounds)
    for lo in range(0, x.shape[0], step):
        hi = min(lo + step, x.shape[0])
        for p in range(P):
            rows = np.ascontiguousarray(x[lo:hi][pids[lo:hi] == p])
            if rows.shape[0]:
                pset.add_batch(p, rows, max_id=x.shape[0], now_ms=0.0)
        pset.flush_all()


def _state(pset, P):
    """Exact per-partition skylines (order included) + global digest."""
    snaps = [pset.snapshot(p) for p in range(P)]
    counts, surv, g, pts = pset.global_merge_stats(emit_points=True)
    return snaps, (np.asarray(counts), np.asarray(surv), int(g), pts)


def _assert_identical(a, b, ctx=""):
    sa, ga = a
    sb, gb = b
    for p, (ra, rb) in enumerate(zip(sa, sb)):
        assert ra.shape == rb.shape and ra.tobytes() == rb.tobytes(), (
            f"partition {p} skyline diverges {ctx}"
        )
    assert (ga[0] == gb[0]).all(), f"counts diverge {ctx}"
    assert (ga[1] == gb[1]).all(), f"survivors diverge {ctx}"
    assert ga[2] == gb[2], f"global count diverges {ctx}"
    assert ga[3].tobytes() == gb[3].tobytes(), f"points diverge {ctx}"


def _cascade_env(monkeypatch, on: bool):
    v = "1" if on else "0"
    monkeypatch.setenv("SKYLINE_FLUSH_PREFILTER", v)
    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", v)


@pytest.mark.parametrize("kind", ["uniform", "correlated", "anti"])
@pytest.mark.parametrize("d", [4, 8])
@pytest.mark.parametrize("policy", ["incremental", "lazy", "overlap"])
def test_cascade_byte_identity(monkeypatch, kind, d, policy):
    """Property grid: cascade on vs off is byte-identical — per-partition
    skylines (including row order) and the global merge digest."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    P = 3
    results = {}
    for on in (True, False):
        _cascade_env(monkeypatch, on)
        rng = np.random.default_rng(29)
        pset = PartitionSet(P, d, flush_policy=policy)
        _run_rounds(pset, rng, _gen(rng, 900, d, kind), P)
        results[on] = _state(pset, P)
        if on:
            cs = pset.flush_cascade_stats()
            assert cs["prefilter_enabled"] and cs["mixed_precision"]
            assert cs["prefilter_seen"] > 0
            assert 0 <= cs["prefilter_dropped"] <= cs["prefilter_seen"]
            assert cs["bf16_resolved"] >= 0
        else:
            cs = pset.flush_cascade_stats()
            assert cs["prefilter_dropped"] == 0 and cs["bf16_resolved"] == 0
    _assert_identical(
        results[True], results[False], f"(kind={kind} d={d} policy={policy})"
    )


def test_cascade_actually_drops(monkeypatch):
    """The grid prefilter is live, not vacuously passing: on clustered
    correlated data a later flush round drops a solid fraction."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    _cascade_env(monkeypatch, True)
    rng = np.random.default_rng(5)
    pset = PartitionSet(4, 4)
    _run_rounds(pset, rng, _gen(rng, 4000, 4, "uniform"), 4)
    cs = pset.flush_cascade_stats()
    assert cs["prefilter_dropped"] > 0, cs
    assert cs["prefilter_drop_fraction"] == pytest.approx(
        cs["prefilter_dropped"] / cs["prefilter_seen"]
    )


def test_all_dropped_batch(monkeypatch):
    """A whole batch certified-dropped by the grid: the flush degenerates
    to a no-op for that partition and state matches the exact path."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")

    def run(on):
        _cascade_env(monkeypatch, on)
        rng = np.random.default_rng(11)
        pset = PartitionSet(2, 4)
        strong = (rng.random((64, 4)) * 0.01).astype(np.float32)
        weak = (0.5 + rng.random((300, 4)) * 0.5).astype(np.float32)
        pset.add_batch(0, strong, max_id=64, now_ms=0.0)
        pset.flush_all()  # publishes the grid over the strong skyline
        pset.add_batch(0, weak, max_id=364, now_ms=0.0)
        pset.flush_all()
        return pset, _state(pset, 2)

    pset_on, state_on = run(True)
    _, state_off = run(False)
    _assert_identical(state_on, state_off, "(all-dropped batch)")
    cs = pset_on.flush_cascade_stats()
    assert cs["prefilter_dropped"] == 300, cs  # every weak row certified


def test_nan_inf_rows(monkeypatch):
    """NaN coordinates are dominance-neutral and must never be prefiltered
    (their grid code is -1 on the victim side); +inf rows are droppable.
    Cascade on/off must agree byte for byte either way."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")

    def run(on):
        _cascade_env(monkeypatch, on)
        rng = np.random.default_rng(13)
        pset = PartitionSet(2, 4)
        base = rng.random((400, 4)).astype(np.float32)
        pset.add_batch(0, base, max_id=400, now_ms=0.0)
        pset.flush_all()
        odd = rng.random((200, 4)).astype(np.float32)
        odd[:40, 1] = np.nan  # never droppable
        odd[40:80, 2] = np.inf  # droppable when the other dims certify
        pset.add_batch(0, odd, max_id=600, now_ms=0.0)
        pset.flush_all()
        return pset, _state(pset, 2)

    pset_on, state_on = run(True)
    _, state_off = run(False)
    _assert_identical(state_on, state_off, "(NaN/inf rows)")
    # NaN rows are neither dominated nor dominating: all 40 must survive
    sky0 = state_on[0][0]
    assert np.isnan(sky0).any(axis=1).sum() == 40


def test_bf16_ambiguous_ties(monkeypatch):
    """Duplicates and sub-bf16-resolution near-ties sit inside the margin:
    the bf16 pass must defer them to f32, keeping exact semantics
    (duplicates never dominate each other)."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")

    def run(on):
        _cascade_env(monkeypatch, on)
        rng = np.random.default_rng(17)
        pset = PartitionSet(2, 4)
        base = rng.random((300, 4)).astype(np.float32)
        pset.add_batch(0, base, max_id=300, now_ms=0.0)
        pset.flush_all()
        # exact duplicates of skyline rows + rows nudged by one f32 ulp
        # (far inside the bf16 margin) in a strictly-worse direction
        dup = base[:50].copy()
        nudged = np.nextafter(base[50:100], np.float32(2.0), dtype=np.float32)
        pset.add_batch(
            0, np.concatenate([dup, nudged]), max_id=400, now_ms=0.0
        )
        pset.flush_all()
        return _state(pset, 2)

    _assert_identical(run(True), run(False), "(bf16-ambiguous ties)")


@pytest.mark.parametrize("policy", ["incremental", "lazy"])
def test_meshed_engine_cascade(monkeypatch, policy):
    """Under a mesh the grid prefilter self-disables (host rows feed a
    sharded flush) but the bf16 pass runs inside the shard_map kernels —
    results must match the cascade-off meshed run exactly."""
    import jax

    if not hasattr(jax, "shard_map"):  # same gap that fails test_engine_mesh
        pytest.skip("jax.shard_map unavailable in this jax version")

    def run(on):
        _cascade_env(monkeypatch, on)
        rng = np.random.default_rng(19)
        eng = SkylineEngine(
            EngineConfig(
                parallelism=2, dims=4, domain_max=1.0, buffer_size=256,
                emit_skyline_points=True, flush_policy=policy,
            ),
            mesh=make_mesh(2),
        )
        x = rng.random((3000, 4)).astype(np.float32)
        eng.process_records(np.arange(1500), x[:1500])
        eng.process_trigger("q0,0")
        eng.poll_results()
        eng.process_records(np.arange(1500, 3000), x[1500:])
        eng.process_trigger("q1,0")
        (r,) = eng.poll_results()
        return r, eng.stats()["flush_cascade"]

    r_on, cs_on = run(True)
    r_off, _ = run(False)
    assert r_on["skyline_size"] == r_off["skyline_size"]
    assert_same_set(r_on["skyline_points"], r_off["skyline_points"])
    assert cs_on["prefilter_seen"] == 0  # grid prefilter inert under mesh


def test_sfs_large_skyline_mixed_precision(monkeypatch):
    """The sequential large-skyline path (skyline_large / SFS rounds) with
    the bf16 pass matches the exact path bit for bit, env-gated and via
    the explicit argument."""
    from skyline_tpu.ops.block_skyline import skyline_large

    rng = np.random.default_rng(23)
    x = jnp.asarray(_gen(rng, 6000, 8, "anti"))
    exact = np.asarray(skyline_large(x, block=1024, mp=False))
    fast = np.asarray(skyline_large(x, block=1024, mp=True))
    assert exact.tobytes() == fast.tobytes()
    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", "1")
    gated = np.asarray(skyline_large(x, block=1024))
    assert gated.tobytes() == exact.tobytes()


def test_mask_scan_and_blocked_mixed_precision():
    """Direct mp on/off equality for the jnp fallbacks the global merge
    and multihost paths share."""
    from skyline_tpu.ops.block_skyline import (
        dominated_by_blocked,
        skyline_mask_scan,
    )

    rng = np.random.default_rng(31)
    x = jnp.asarray(_gen(rng, 1500, 8, "uniform"))
    a = np.asarray(skyline_mask_scan(x, chunk=512, mp=False))
    b = np.asarray(skyline_mask_scan(x, chunk=512, mp=True))
    assert (a == b).all()
    y = jnp.asarray(_gen(rng, 700, 8, "correlated"))
    xv = jnp.asarray(rng.random(1500) < 0.9)
    da = np.asarray(dominated_by_blocked(y, x, x_valid=xv, block=256))
    db = np.asarray(
        dominated_by_blocked(y, x, x_valid=xv, block=256, mp=True)
    )
    assert (da == db).all()


def test_strictly_dominated_bf16_sound(rng):
    """Certification soundness: every row the bf16 margin pass flags has a
    genuine strict dominator in exact f32; ties and duplicates are never
    certified."""
    from skyline_tpu.ops.dominance import strictly_dominated_bf16

    x = rng.random((400, 6)).astype(np.float32)
    y = rng.random((500, 6)).astype(np.float32)
    xv = rng.random(400) < 0.8
    got = np.asarray(
        strictly_dominated_bf16(jnp.asarray(y), jnp.asarray(x), jnp.asarray(xv))
    )
    strict = (
        (x[xv][:, None, :] < y[None, :, :]).all(axis=2).any(axis=0)
    )
    assert not (got & ~strict).any(), "certified a non-dominated row"
    assert got.sum() > 0  # the pass is live on easy data
    # self-vs-self: a certified row still needs a strict dominator; the
    # diagonal (each row vs itself) can never certify
    self_got = np.asarray(
        strictly_dominated_bf16(jnp.asarray(x), jnp.asarray(x))
    )
    self_strict = (
        (x[:, None, :] < x[None, :, :]).all(axis=2).any(axis=0)
    )
    assert not (self_got & ~self_strict).any()
    # a pure tie pair (shared coordinate) is never certified
    pair = np.array([[1.0, 2.0, 3.0], [1.0, 30.0, 40.0]], dtype=np.float32)
    assert not np.asarray(
        strictly_dominated_bf16(jnp.asarray(pair), jnp.asarray(pair))
    ).any()


def test_grid_summary_codes_sound(rng):
    """Stage-1 soundness: whenever every dim has rep-code < victim-code,
    the rep row strictly dominates the victim in exact f32 (the inequality
    chain x <= b[ux] < b[vy] <= y the prefilter relies on)."""
    from skyline_tpu.stream.window import (
        GRID_BINS,
        GRID_REPS,
        grid_summary_device,
    )

    d, cap, count = 5, 1024, 200
    sky = np.full((1, cap, d), np.inf, dtype=np.float32)
    rows = rng.random((count, d)).astype(np.float32)
    sky[0, :count] = rows
    counts = jnp.asarray(np.array([count], dtype=np.int32))
    bounds, ux = grid_summary_device(jnp.asarray(sky), counts, cap)
    bounds = np.asarray(bounds)[0]  # (K+1, d)
    ux = np.asarray(ux)[0]  # (R, d)
    assert np.all(np.diff(bounds, axis=0) > 0)
    r = min(cap, GRID_REPS)
    assert ux.shape == (r, d) and (ux[:count] <= GRID_BINS).all()
    assert (ux[count:] == GRID_BINS + 1).all()  # padding reps masked out
    y = rng.random((800, d)).astype(np.float32) * 1.5
    vy = (bounds[None, :, :] <= y[:, None, :]).sum(axis=1) - 1
    dominated = np.any(
        np.all(ux[None, :, :] < vy[:, None, :], axis=2), axis=1
    )
    strict = (rows[:r][None, :, :] < y[:, None, :]).all(axis=2).any(axis=1)
    assert not (dominated & ~strict).any(), "grid certified a false drop"
    assert dominated.sum() > 0  # and it certifies real ones


def test_engine_stats_and_telemetry_counters(monkeypatch):
    """The flush_cascade block rides engine.stats() and the counters reach
    the telemetry hub under their Prometheus names."""
    from skyline_tpu.telemetry import Telemetry

    _cascade_env(monkeypatch, True)
    hub = Telemetry()
    eng = SkylineEngine(
        EngineConfig(parallelism=2, dims=4, domain_max=1.0, buffer_size=128),
        telemetry=hub,
    )
    rng = np.random.default_rng(37)
    x = rng.random((2000, 4)).astype(np.float32)
    eng.process_records(np.arange(1000), x[:1000])
    eng.process_trigger("q0,0")
    eng.poll_results()
    eng.process_records(np.arange(1000, 2000), x[1000:])
    eng.process_trigger("q1,0")
    eng.poll_results()
    st = eng.stats()
    cs = st["flush_cascade"]
    for key in (
        "prefilter_enabled",
        "mixed_precision",
        "prefilter_seen",
        "prefilter_dropped",
        "prefilter_drop_fraction",
        "bf16_resolved",
    ):
        assert key in cs, cs
    assert cs["prefilter_seen"] > 0
    body = hub.render_prometheus()
    assert "skyline_flush_prefilter_dropped_total" in body
    assert "skyline_flush_bf16_resolved_total" in body
    # telemetry totals agree with the stats block (stats() synced them)
    assert hub.counters.get("flush.prefilter_dropped") == cs[
        "prefilter_dropped"
    ]
    assert hub.counters.get("flush.bf16_resolved") == cs["bf16_resolved"]


def test_restore_invalidates_grid(monkeypatch, tmp_path):
    """A restored checkpoint must invalidate the device grid summaries —
    stale cells over pre-restore state could otherwise certify drops
    against a skyline that no longer exists."""
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    _cascade_env(monkeypatch, True)
    rng = np.random.default_rng(41)
    eng = SkylineEngine(
        EngineConfig(parallelism=2, dims=4, domain_max=1.0, buffer_size=128)
    )
    x = rng.random((1500, 4)).astype(np.float32)
    eng.process_records(np.arange(1500), x)
    eng.process_trigger("q0,0")
    eng.poll_results()
    path = str(tmp_path / "ck.npz")
    save_engine(eng, path)
    eng2 = load_engine(path)
    assert eng2.pset._grid_dev is None
    assert eng2.pset._grid_host is None
    assert eng2.pset._grid_epoch is None
    # and the restored engine still answers identically with the cascade on
    eng2.process_trigger("q1,0")
    (r2,) = eng2.poll_results()
    eng.process_trigger("q1,0")
    (r1,) = eng.poll_results()
    assert r1["skyline_size"] == r2["skyline_size"]
