"""Sharded two-phase skyline: correctness + invariance over device counts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skyline_tpu.ops import skyline_np, pad_window
from skyline_tpu.parallel import make_mesh
from skyline_tpu.parallel.mesh import build_two_phase, shard_rows

from conftest import sorted_rows as _sorted_rows


@pytest.mark.parametrize("n_dev", [1, 2, 4, 8])
def test_two_phase_matches_oracle(rng, n_dev):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh(n_dev)
    step = build_two_phase(mesh, local_block=64, cross_block=128)
    n, d = 512, 3
    x = rng.uniform(0, 1000, size=(n, d)).astype(np.float32)
    vals, valid = pad_window(x, n)  # no-op pad; exact fit
    xs, vs = shard_rows(mesh, np.asarray(vals), np.asarray(valid))
    local_keep, global_keep = step(xs, vs)
    got = x[np.asarray(global_keep)]
    np.testing.assert_allclose(_sorted_rows(got), _sorted_rows(skyline_np(x)))
    # local phase must be a superset of the global skyline
    assert (np.asarray(local_keep) | ~np.asarray(global_keep)).all()


def test_device_count_invariance(rng):
    # The result must not depend on how many devices the window is sharded
    # over (the invariant the reference checks only by comparing CSVs by eye,
    # SURVEY.md §4 item 3).
    n, d = 1024, 4
    x = rng.uniform(0, 1000, size=(n, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    results = []
    for n_dev in (1, 4, 8):
        mesh = make_mesh(n_dev)
        step = build_two_phase(mesh, local_block=64, cross_block=256)
        xs, vs = shard_rows(mesh, x, valid)
        _, gk = step(xs, vs)
        results.append(_sorted_rows(x[np.asarray(gk)]))
    np.testing.assert_allclose(results[0], results[1])
    np.testing.assert_allclose(results[0], results[2])


def test_two_phase_with_invalid_rows(rng):
    # padding rows sharded onto devices must never surface as survivors
    mesh = make_mesh(4)
    step = build_two_phase(mesh, local_block=32, cross_block=64)
    n, d = 256, 2
    x = rng.uniform(0, 1000, size=(200, d)).astype(np.float32)
    vals, valid = pad_window(x, n)
    # scatter the valid rows across shards unevenly: interleave pads
    perm = rng.permutation(n)
    vals = np.asarray(vals)[perm]
    valid = np.asarray(valid)[perm]
    xs, vs = shard_rows(mesh, vals, valid)
    _, gk = step(xs, vs)
    gk = np.asarray(gk)
    assert not (gk & ~valid).any()
    np.testing.assert_allclose(
        _sorted_rows(vals[gk]), _sorted_rows(skyline_np(x))
    )
