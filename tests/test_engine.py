"""Engine tests: barrier semantics, cross-partitioner agreement, metrics."""

import numpy as np
import pytest

from skyline_tpu.ops import skyline_np
from skyline_tpu.stream import EngineConfig, SkylineEngine

from conftest import assert_same_set


def _feed(engine, values, start_id=0):
    ids = np.arange(start_id, start_id + values.shape[0], dtype=np.int64)
    engine.process_records(ids, values)
    return start_id + values.shape[0]


@pytest.mark.parametrize("algo", ["mr-dim", "mr-grid", "mr-angle"])
def test_result_matches_oracle(rng, algo):
    cfg = EngineConfig(parallelism=2, algo=algo, domain_max=1000.0, dims=3,
                       buffer_size=256, emit_skyline_points=True)
    eng = SkylineEngine(cfg)
    x = rng.uniform(0, 1000, size=(5000, 3)).astype(np.float32)
    _feed(eng, x)
    eng.process_trigger("0,4000")
    results = eng.poll_results()
    assert len(results) == 1
    r = results[0]
    assert r["query_id"] == "0"
    assert r["record_count"] == 4000
    expect = skyline_np(x)
    assert r["skyline_size"] == expect.shape[0]
    assert_same_set(np.asarray(r["skyline_points"]), expect)


def test_cross_partitioner_agreement(rng):
    # The partitioning strategy must not change the skyline, only the timing
    # (SURVEY.md §4 item 3 — the reference checks this by eyeballing CSVs).
    x = rng.uniform(0, 1000, size=(3000, 4)).astype(np.float32)
    sizes = set()
    for algo in ("mr-dim", "mr-grid", "mr-angle"):
        eng = SkylineEngine(EngineConfig(parallelism=4, algo=algo, dims=4,
                                         buffer_size=512))
        _feed(eng, x)
        # immediate trigger (required=0): sparse partitions (e.g. mr-angle's
        # edge sectors on uniform data) hold old ids and would defer a high
        # barrier indefinitely — reference-faithful but not what's under test
        eng.process_trigger("0,0")
        (r,) = eng.poll_results()
        sizes.add(r["skyline_size"])
    assert len(sizes) == 1


def test_barrier_defers_until_id_reached(rng):
    cfg = EngineConfig(parallelism=1, algo="mr-dim", dims=2, buffer_size=64)
    eng = SkylineEngine(cfg)
    x1 = rng.uniform(100, 1000, size=(100, 2)).astype(np.float32)
    _feed(eng, x1)  # ids 0..99
    eng.process_trigger("0,450")  # barrier at id 450: must NOT fire yet
    assert eng.poll_results() == []
    assert eng.inflight_queries == 1
    x2 = rng.uniform(100, 1000, size=(401, 2)).astype(np.float32)
    _feed(eng, x2, start_id=100)  # ids 100..500 -> barrier reached
    results = eng.poll_results()
    assert len(results) == 1
    # result reflects ALL records seen at trigger satisfaction
    assert results[0]["skyline_size"] == skyline_np(
        np.concatenate([x1, x2])
    ).shape[0]


def test_empty_partition_answers_immediately(rng):
    # currentMaxId == -1 fast-path (FlinkSkyline.java:351): a never-fed
    # partition answers at once, so queries complete even under extreme skew.
    cfg = EngineConfig(parallelism=4, algo="mr-dim", dims=2, buffer_size=64)
    eng = SkylineEngine(cfg)
    # all data in partition 0 (dim0 < domain/8)
    x = rng.uniform(0, 100, size=(200, 2)).astype(np.float32)
    x[:, 0] = rng.uniform(0, 1000.0 / 8 - 1, size=200)
    _feed(eng, x)
    eng.process_trigger("0,199")
    results = eng.poll_results()
    assert len(results) == 1
    assert results[0]["skyline_size"] == skyline_np(x).shape[0]


def test_trigger_without_count_fires_immediately(rng):
    eng = SkylineEngine(EngineConfig(parallelism=2, algo="mr-angle", dims=2,
                                     buffer_size=64))
    _feed(eng, rng.uniform(0, 1000, size=(50, 2)).astype(np.float32))
    eng.process_trigger("3")  # bare algo-id payload (query_trigger.py:58-62)
    (r,) = eng.poll_results()
    assert r["query_id"] == "3"
    assert r["record_count"] == "unknown"


def test_metrics_fields_present_and_sane(rng):
    eng = SkylineEngine(EngineConfig(parallelism=2, algo="mr-grid", dims=2,
                                     buffer_size=128))
    _feed(eng, rng.uniform(0, 1000, size=(1000, 2)).astype(np.float32))
    eng.process_trigger("0,900")
    (r,) = eng.poll_results()
    for k in (
        "ingestion_time_ms",
        "local_processing_time_ms",
        "global_processing_time_ms",
        "total_processing_time_ms",
        "query_latency_ms",
    ):
        assert r[k] >= 0
    assert 0.0 <= r["optimality"] <= 1.0


@pytest.mark.parametrize("algo", ["mr-dim", "mr-grid", "mr-angle"])
def test_lazy_policy_matches_incremental_and_oracle(rng, algo):
    # the lazy (SFS-at-query) policy must produce the exact same skyline as
    # the incremental policy and the numpy oracle, chunked feed and all
    x = rng.uniform(0, 1000, size=(3000, 3)).astype(np.float32)
    results = {}
    for policy in ("incremental", "lazy"):
        eng = SkylineEngine(
            EngineConfig(parallelism=2, algo=algo, dims=3, buffer_size=256,
                         flush_policy=policy, emit_skyline_points=True)
        )
        for i in range(0, x.shape[0], 500):
            _feed(eng, x[i : i + 500], start_id=i)
        eng.process_trigger("0,0")
        (results[policy],) = eng.poll_results()
    oracle = skyline_np(x)
    for policy, r in results.items():
        assert r["skyline_size"] == oracle.shape[0], policy
        assert_same_set(np.asarray(r["skyline_points"]), oracle)
    assert results["lazy"]["optimality"] == pytest.approx(
        results["incremental"]["optimality"]
    )


def test_lazy_policy_under_extreme_skew(rng):
    # mr-dim with clustered dim0 routes nearly everything to one partition:
    # exercises the sequential (per-partition) SFS path and the
    # union-compacted global merge; results must still match the oracle
    x = np.column_stack([
        rng.uniform(0, 50, size=6000),  # all in the lowest dim0 range
        rng.uniform(0, 1000, size=6000),
        rng.uniform(0, 1000, size=6000),
    ]).astype(np.float32)
    eng = SkylineEngine(
        EngineConfig(parallelism=4, algo="mr-dim", dims=3, domain_max=1000.0,
                     flush_policy="lazy", emit_skyline_points=True)
    )
    for i in range(0, 6000, 1000):
        _feed(eng, x[i : i + 1000], start_id=i)
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    oracle = skyline_np(x)
    assert r["skyline_size"] == oracle.shape[0]
    assert_same_set(np.asarray(r["skyline_points"]), oracle)
    # second query re-runs the skew path on non-empty state
    y = rng.uniform(0, 1000, size=(3000, 3)).astype(np.float32)
    _feed(eng, y, start_id=6000)
    eng.process_trigger("1,0")
    (r2,) = eng.poll_results()
    assert r2["skyline_size"] == skyline_np(np.concatenate([x, y])).shape[0]


def test_lazy_policy_sequential_queries(rng):
    # second query under lazy hits the non-empty-initial-state path (SFS
    # append + old-vs-new cleanup); dominated old skyline rows must vanish
    eng = SkylineEngine(
        EngineConfig(parallelism=2, algo="mr-angle", dims=2, buffer_size=128,
                     flush_policy="lazy", emit_skyline_points=True)
    )
    x1 = rng.uniform(500, 1000, size=(400, 2)).astype(np.float32)
    nid = _feed(eng, x1)
    eng.process_trigger("0,0")
    (r1,) = eng.poll_results()
    assert_same_set(np.asarray(r1["skyline_points"]), skyline_np(x1))
    x2 = rng.uniform(0, 1000, size=(400, 2)).astype(np.float32)
    _feed(eng, x2, start_id=nid)
    eng.process_trigger("1,0")
    (r2,) = eng.poll_results()
    both = np.concatenate([x1, x2])
    assert_same_set(np.asarray(r2["skyline_points"]), skyline_np(both))


def test_device_fast_path_matches_straggler_path(rng):
    # same workload, two dispatch patterns: trigger-after-ingest (device
    # fast path) vs trigger-before-last-chunk (host straggler path) must
    # agree on the skyline
    x = rng.uniform(0, 1000, size=(2000, 2)).astype(np.float32)
    cfg = dict(parallelism=2, algo="mr-dim", dims=2, buffer_size=128,
               emit_skyline_points=True)
    fast = SkylineEngine(EngineConfig(**cfg))
    _feed(fast, x)
    fast.process_trigger("0,0")  # all barriers pass -> device fast path
    (rf,) = fast.poll_results()
    slow = SkylineEngine(EngineConfig(**cfg))
    _feed(slow, x[:1000])  # ids 0..999: every partition's max id < 1500
    slow.process_trigger("0,1500")  # -> all partitions defer (host path)
    assert slow.poll_results() == []
    _feed(slow, x[1000:], start_id=1000)  # barriers clear mid-routing
    (rs,) = slow.poll_results()
    assert rf["skyline_size"] == rs["skyline_size"]
    assert_same_set(
        np.asarray(rf["skyline_points"]), np.asarray(rs["skyline_points"])
    )


def test_timing_invariant_straggler_midcall_answers(rng):
    # Round-3 regression (second deploy-artifact violation): a deferred
    # query whose barriers clear DURING one process_records call — the
    # first partition's snapshot flush (incl. compile) takes real wall that
    # later partitions' arrivals must not predate. Injected constant clocks
    # make any lost wall time break total >= local deterministically.
    eng = SkylineEngine(
        EngineConfig(parallelism=2, algo="mr-dim", dims=7, buffer_size=500000)
    )
    x = rng.uniform(0, 1000, size=(30000, 7)).astype(np.float32)
    ids = np.arange(x.shape[0], dtype=np.int64)
    eng.process_records(ids[:20000], x[:20000], now_ms=1000.0)
    eng.process_trigger("0,25000", now_ms=1500.0)  # defers on all partitions
    assert eng.poll_results() == []
    # one call clears every barrier; all flush work lands in the first
    # partition's snapshot inside this call
    eng.process_records(ids[20000:], x[20000:], now_ms=2000.0)
    (r,) = eng.poll_results()
    assert r["local_processing_time_ms"] > 0
    assert r["total_processing_time_ms"] >= r["local_processing_time_ms"]
    assert r["total_processing_time_ms"] >= r["global_processing_time_ms"]
    assert r["ingestion_time_ms"] >= 0


def test_timing_decomposition_invariant(rng):
    # Regression (round-2 deploy artifact: LocalTime 3713 > TotalTime 2660):
    # trigger-time snapshot flush wall (incl. first-query jit compile) must
    # advance the arrival clock, so total >= local always holds
    # (FlinkSkyline.java:579-588 semantics: total is job-start -> emit).
    # Injected constant clock + a buffer larger than the feed forces ALL
    # flush work into the snapshot path — the exact previously-broken case.
    eng = SkylineEngine(
        EngineConfig(parallelism=2, algo="mr-angle", dims=5, buffer_size=100000)
    )
    x = rng.uniform(0, 1000, size=(20000, 5)).astype(np.float32)
    ids = np.arange(x.shape[0], dtype=np.int64)
    eng.process_records(ids, x, now_ms=1000.0)
    eng.process_trigger("0,0", now_ms=1000.0)
    (r,) = eng.poll_results()
    assert r["local_processing_time_ms"] > 0  # the flush really ran here
    assert r["total_processing_time_ms"] >= r["local_processing_time_ms"]
    assert r["total_processing_time_ms"] >= r["global_processing_time_ms"]
    assert r["ingestion_time_ms"] >= 0
    assert r["query_latency_ms"] >= r["total_processing_time_ms"] - 1


def test_multiple_sequential_queries_reset_state(rng):
    # per-query state must reset (FlinkSkyline.java:652-657): a second query
    # over more data completes and reflects the larger prefix
    eng = SkylineEngine(EngineConfig(parallelism=2, algo="mr-dim", dims=2,
                                     buffer_size=64))
    x1 = rng.uniform(500, 1000, size=(300, 2)).astype(np.float32)
    nid = _feed(eng, x1)
    eng.process_trigger("0,250")
    (r1,) = eng.poll_results()
    # second wave spans the full domain (so every partition keeps receiving
    # ids and the barrier clears) and dominates much of the first
    x2 = rng.uniform(0, 1000, size=(300, 2)).astype(np.float32)
    _feed(eng, x2, start_id=nid)
    eng.process_trigger("1,550")
    (r2,) = eng.poll_results()
    assert r1["query_id"] == "0" and r2["query_id"] == "1"
    assert r2["skyline_size"] == skyline_np(np.concatenate([x1, x2])).shape[0]


def test_incremental_flush_equals_batch(rng):
    # feeding in many tiny batches (exercising incremental merges) must give
    # the same skyline as one big batch
    x = rng.uniform(0, 1000, size=(2000, 3)).astype(np.float32)
    eng_inc = SkylineEngine(EngineConfig(parallelism=2, algo="mr-angle", dims=3,
                                         buffer_size=64))
    sid = 0
    for chunk in np.array_split(x, 37):
        sid = _feed(eng_inc, chunk.astype(np.float32), start_id=sid)
    eng_inc.process_trigger("0,1900")
    (ri,) = eng_inc.poll_results()
    assert ri["skyline_size"] == skyline_np(x).shape[0]


def test_query_timeout_emits_partial(rng):
    # failure detection: the reference's aggregator hangs forever if a
    # partition never reports (SURVEY.md §5); with query_timeout_ms set the
    # engine emits a partial result naming the missing partitions
    cfg = EngineConfig(parallelism=1, algo="mr-dim", dims=2, buffer_size=64,
                       query_timeout_ms=1.0)
    eng = SkylineEngine(cfg)
    x = rng.uniform(0, 400, size=(100, 2)).astype(np.float32)  # partition 0 only
    _feed(eng, x)
    eng.process_trigger("0,5000")  # barrier partition 0 can never clear
    assert eng.poll_results() == []
    import time as _t
    _t.sleep(0.01)
    assert eng.check_timeouts() == 1
    (r,) = eng.poll_results()
    assert r["partial"] is True
    assert 0 in r["missing_partitions"]
    # partition 1 was empty (-1) and answered immediately with an empty
    # skyline; partition 0 is the missing one, so the partial merge is empty
    assert r["skyline_size"] == 0
    assert eng.inflight_queries == 0


def test_no_timeout_when_disabled(rng):
    eng = SkylineEngine(EngineConfig(parallelism=1, algo="mr-dim", dims=2,
                                     buffer_size=64))
    _feed(eng, rng.uniform(0, 400, size=(50, 2)).astype(np.float32))
    eng.process_trigger("0,5000")
    assert eng.check_timeouts() == 0
    assert eng.inflight_queries == 1  # reference behavior: waits forever


def test_grid_prefilter_exact_and_barrier_safe(rng):
    # J10 done right: same skyline with and without the prefilter, and the
    # barrier still clears even when whole batches are dropped
    x = rng.uniform(0, 1000, size=(4000, 3)).astype(np.float32)
    base = SkylineEngine(EngineConfig(parallelism=2, algo="mr-grid", dims=3,
                                      buffer_size=256))
    _feed(base, x)
    base.process_trigger("0,0")
    (rb,) = base.poll_results()

    filt = SkylineEngine(EngineConfig(parallelism=2, algo="mr-grid", dims=3,
                                      buffer_size=256, grid_prefilter=True))
    _feed(filt, x)
    # mixed tail: normal rows (spread over all partitions) then doomed rows
    # (all dims > mid -> the top grid cell); the doomed ids are the HIGHEST,
    # so the top cell's partition clears the barrier only if dropped rows
    # still advance it
    normal = rng.uniform(0, 1000, size=(200, 3)).astype(np.float32)
    doomed = rng.uniform(600, 1000, size=(100, 3)).astype(np.float32)
    filt.process_trigger("1,4150")  # inside the normal tail
    assert filt.poll_results() == []
    _feed(filt, normal, start_id=4000)      # ids 4000..4199
    before = filt.prefiltered  # uniform feeds also shed their all-high rows
    _feed(filt, doomed, start_id=4200)      # ids 4200..4299, all dropped
    (rf,) = filt.poll_results()
    assert rf["query_id"] == "1"
    assert filt.prefiltered - before == 100
    # the top-cell partition's barrier advanced via dropped rows' ids
    top_cell_pid = 7 % filt.config.num_partitions
    assert filt.partitions[top_cell_pid].max_seen_id == 4299
    # doomed rows are all dominated, so the skyline matches the unfiltered
    # oracle over the kept rows
    full = np.concatenate([x, normal])
    assert rf["skyline_size"] == skyline_np(
        np.concatenate([full, doomed])
    ).shape[0] == skyline_np(full).shape[0]
    assert rb["skyline_size"] == skyline_np(x).shape[0]


def test_grid_prefilter_waits_for_witness():
    # without a witness (no tuple <= midpoint in all dims), nothing may be
    # dropped — the midpoint alone is not a real dominator
    eng = SkylineEngine(EngineConfig(parallelism=1, algo="mr-grid", dims=2,
                                     domain_max=1000.0, buffer_size=64,
                                     grid_prefilter=True))
    high = np.array([[800.0, 600.0], [600.0, 800.0]], dtype=np.float32)
    eng.process_records(np.arange(2, dtype=np.int64), high)
    assert eng.prefiltered == 0
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    assert r["skyline_size"] == 2  # both incomparable, both kept


def test_stats_surface(rng):
    eng = SkylineEngine(EngineConfig(parallelism=2, algo="mr-dim", dims=2,
                                     domain_max=100.0, buffer_size=64))
    x = rng.uniform(0, 100, size=(500, 2)).astype(np.float32)
    eng.process_records(np.arange(500), x)
    s = eng.stats(include_skyline_counts=True)
    assert s["records_in"] == 500
    assert sum(s["partitions"]["records_seen"]) == 500
    assert s["inflight_queries"] == 0 and not s["meshed"]
    # a 500-row batch over buffer_size=64 always triggers the set-wide
    # flush, so nothing may remain pending
    assert s["pending_flush_rows"] == 0
    assert len(s["partitions"]["skyline_counts"]) == 4
    eng.process_trigger("0,0")
    eng.poll_results()
    assert eng.stats()["inflight_queries"] == 0


@pytest.mark.parametrize("algo", ["mr-dim", "mr-grid", "mr-angle"])
def test_single_dimension_window(rng, algo):
    """d=1 degenerate case: partition ids stay in range for every strategy
    (mr-angle has zero angle terms at d=1) and the skyline is the minimum."""
    x = rng.uniform(0, 1000, (500, 1)).astype(np.float32)
    eng = SkylineEngine(EngineConfig(parallelism=4, algo=algo, dims=1,
                                     domain_max=1000.0, flush_policy="lazy",
                                     emit_skyline_points=True))
    _feed(eng, x)
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    # the exact minimum must survive — a partitioner that routed it (or any
    # record) out of range would still report size 1 at d=1
    assert r["skyline_size"] == 1
    assert float(np.asarray(r["skyline_points"]).min()) == float(x.min())


@pytest.mark.parametrize("algo", ["mr-dim", "mr-angle"])
def test_high_dimension_window_matches_oracle(rng, algo):
    """d=16 (the Pallas kernels' documented unroll ceiling) through the
    full engine: routing, lazy SFS flush, global merge — exact vs oracle.
    No other test goes above d=8, so this pins the top of the range."""
    n, d = 4000, 16
    x = rng.uniform(0, 1000, size=(n, d)).astype(np.float32)
    cfg = EngineConfig(parallelism=4, algo=algo, dims=d,
                       domain_max=1000.0, flush_policy="lazy",
                       emit_skyline_points=True)
    eng = SkylineEngine(cfg)
    _feed(eng, x)
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    want = skyline_np(x)
    assert r["skyline_size"] == want.shape[0]
    assert_same_set(r["skyline_points"], want)
