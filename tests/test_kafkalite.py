"""kafkalite: real-wire-protocol Kafka path (J9, FlinkSkyline.java:84-97,
177-183) exercised over actual TCP against the embedded broker —
earliest/latest offset semantics, the 10 MB message cap, CRC validation,
and the full producer -> worker -> collector loop."""

import numpy as np
import pytest

from skyline_tpu.bridge.kafkalite import (
    Broker,
    KafkaLiteConsumer,
    KafkaLiteProducer,
    MessageSizeTooLargeError,
)
from skyline_tpu.bridge.kafkalite import protocol as P


@pytest.fixture
def broker():
    with Broker() as b:
        yield b


def test_record_batch_roundtrip():
    records = [(None, b"0,1,2"), (b"k", b"1,3,4"), (None, b"")]
    blob = P.encode_record_batch(records, base_offset=7)
    out = P.decode_record_batches(blob)
    assert [(o, k, v) for o, k, v in out] == [
        (7, None, b"0,1,2"),
        (8, b"k", b"1,3,4"),
        (9, None, b""),
    ]


def test_record_batch_crc_detects_corruption():
    blob = bytearray(P.encode_record_batch([(None, b"payload")]))
    blob[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC32C"):
        P.decode_record_batches(bytes(blob))


def test_crc32c_known_vector():
    # RFC 3720 test vector: 32 zero bytes -> 0x8A9136AA
    assert P.crc32c(b"\x00" * 32) == 0x8A9136AA


def test_produce_fetch_roundtrip(broker):
    prod = KafkaLiteProducer(broker.address)
    cons = KafkaLiteConsumer("t", broker.address, auto_offset_reset="earliest")
    for i in range(10):
        prod.send("t", f"{i},{i * 2}")
    prod.flush()
    got = []
    for _ in range(20):
        got.extend(cons.poll())
        if len(got) >= 10:
            break
    assert got == [f"{i},{i * 2}" for i in range(10)]
    prod.close()
    cons.close()


def test_earliest_vs_latest_offsets(broker):
    """The reference's split: data topic earliest, query topic latest
    (FlinkSkyline.java:84-97)."""
    prod = KafkaLiteProducer(broker.address)
    prod.send("topic", "old-1")
    prod.flush()
    early = KafkaLiteConsumer(
        "topic", broker.address, auto_offset_reset="earliest"
    )
    late = KafkaLiteConsumer(
        "topic", broker.address, auto_offset_reset="latest"
    )
    assert early.poll() == ["old-1"]
    assert late.poll(timeout_ms=10) == []  # pre-subscription history skipped
    prod.send("topic", "new-1")
    prod.flush()
    assert late.poll() == ["new-1"]
    assert early.poll() == ["new-1"]
    for c in (early, late):
        c.close()
    prod.close()


def test_message_too_large_cap():
    """The 10 MB cap, client side and broker side
    (docker-compose.yml:20-21, FlinkSkyline.java:179)."""
    with Broker(max_message_bytes=1024) as b:
        prod = KafkaLiteProducer(b.address, max_request_size=512)
        with pytest.raises(MessageSizeTooLargeError):
            prod.send("t", "x" * 600)
        # under the client cap but over the broker cap -> broker rejects
        prod2 = KafkaLiteProducer(b.address, max_request_size=10_000)
        prod2.send("t", "y" * 2000)
        with pytest.raises(MessageSizeTooLargeError):
            prod2.flush()


def test_multi_batch_resume_offsets(broker):
    """A consumer that joins mid-stream resumes from its fetch offset, not
    batch starts."""
    prod = KafkaLiteProducer(broker.address)
    for i in range(5):
        prod.send("m", f"a{i}")
    prod.flush()
    cons = KafkaLiteConsumer("m", broker.address)
    first = cons.poll(max_records=3)
    assert first == ["a0", "a1", "a2"]
    rest = cons.poll()
    assert rest == ["a3", "a4"]
    for i in range(3):
        prod.send("m", f"b{i}")
    prod.flush()
    assert cons.poll() == ["b0", "b1", "b2"]


def test_kafkabus_worker_end_to_end(broker):
    """The reference's full loop over REAL TCP: producer wire lines ->
    broker -> SkylineWorker -> result JSON -> collector consumer. Mirrors
    the MemoryBus e2e in test_bridge_e2e.py but through the Kafka plane."""
    from skyline_tpu.bridge.kafka import KafkaBus
    from skyline_tpu.bridge.wire import parse_result
    from skyline_tpu.bridge.worker import SkylineWorker
    from skyline_tpu.ops.dominance import skyline_np
    from skyline_tpu.stream.engine import EngineConfig

    bus = KafkaBus(broker.address)
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=2, algo="mr-dim", dims=2,
                          domain_max=100.0, buffer_size=64)
    )
    out = bus.consumer("output-skyline", from_beginning=True)

    rng = np.random.default_rng(5)
    x = rng.integers(0, 101, size=(500, 2))
    bus.produce_many(
        "input-tuples", [f"{i},{r[0]},{r[1]}" for i, r in enumerate(x)]
    )
    # barrier 450 on a 500-record stream: every partition (4, ~125 records
    # each) sees some of ids 450-499, so the id barrier clears everywhere
    # (a 499 barrier would strand sparse partitions — the reference's
    # finite-stream heuristic-barrier quirk, SURVEY.md §3.3)
    bus.produce("queries", "0,450")
    for _ in range(50):
        worker.step()
        results = out.poll()
        if results:
            break
    assert len(results) == 1
    res = parse_result(results[0])
    assert res["query_id"] == "0"
    assert res["skyline_size"] == skyline_np(x.astype(np.float32)).shape[0]
    bus.close()


def test_flush_restores_buffer_on_connection_error(broker):
    """A transient fault mid-flush must not lose buffered records: the
    connection reconnects under its retry budget and the buffered batch
    lands exactly once, in order (kafka-python keeps unacked batches the
    same way)."""
    prod = KafkaLiteProducer(broker.address)
    prod.send("r", "keep-1")
    prod.send("r", "keep-2")
    prod._conn._sock.close()  # simulate a dropped connection
    prod.flush()  # reconnects transparently and re-sends the batch
    assert prod._conn.reconnects >= 1
    cons = KafkaLiteConsumer("r", broker.address)
    got = cons.poll()
    assert got == ["keep-1", "keep-2"]


def test_api_versions_negotiation(broker):
    """KIP-511: a v>0 ApiVersions request gets UNSUPPORTED_VERSION in the v0
    body, so modern clients downgrade instead of misparsing; v0 lists the
    supported api ranges."""
    from skyline_tpu.bridge.kafkalite.client import _Connection

    conn = _Connection(broker.address, "probe")
    r = conn.request(P.API_API_VERSIONS, 3, b"")
    assert r.int16() == P.ERR_UNSUPPORTED_VERSION
    r = conn.request(P.API_API_VERSIONS, 0, b"")
    assert r.int16() == P.ERR_NONE
    ranges = {k: (lo, hi) for k, lo, hi in
              r.array(lambda rr: (rr.int16(), rr.int16(), rr.int16()))}
    assert ranges[P.API_PRODUCE][1] >= 3 and ranges[P.API_FETCH][1] >= 4
    conn.close()


def test_send_many_multi_slice_preserves_every_record(broker):
    """produce_many above linger_records exercises send_many's slice/flush
    loop (the hot path of the benchmark streams): every record must arrive
    exactly once, in order, and oversized records must be rejected before
    any buffering."""
    from skyline_tpu.bridge.kafka import KafkaBus
    from skyline_tpu.bridge.kafkalite.client import (
        KafkaLiteConsumer,
        MessageSizeTooLargeError,
    )

    bus = KafkaBus(broker.address)
    n = 10_000  # > linger_records=4096: at least three slices
    msgs = [f"{i},{i}.5" for i in range(n)]
    bus.produce_many("slices", msgs)
    cons = KafkaLiteConsumer("slices", broker.address)
    got = []
    while len(got) < n:
        batch = cons.poll(4096)
        if not batch:
            break
        got.extend(batch)
    assert got == msgs

    import pytest

    with pytest.raises(MessageSizeTooLargeError):
        bus._producer.send_many("slices", ["x" * (11 * 1024 * 1024)])
    # the rejected call buffered nothing: a flush ships no new records
    bus._producer.flush()
    assert cons.poll(10) == []


def test_pending_buffer_and_offset_reset_semantics(broker):
    """Offset-reset (log truncated under the consumer) semantics with the
    pending buffer: records already decoded are served BEFORE the reset can
    be observed (poll early-returns on a non-empty buffer, so a fetch — the
    only place OFFSET_OUT_OF_RANGE appears — never runs with content); the
    reset then re-resolves and replays from earliest. Normal at-least-once
    behavior, same as kafka-python."""
    prod = KafkaLiteProducer(broker.address)
    for i in range(10):
        prod.send("oor", f"old-{i}")
    prod.flush()
    cons = KafkaLiteConsumer("oor", broker.address)
    assert cons.poll(3) == ["old-0", "old-1", "old-2"]
    assert len(cons._pending) == 7  # rest of the blob buffered
    cons._offset = 10_000  # simulate: position now past the high watermark
    # buffered records surface first — the poisoned offset is not consulted
    assert cons.poll(4) == [f"old-{i}" for i in range(3, 7)]
    assert cons.poll(4) == [f"old-{i}" for i in range(7, 10)]
    # buffer empty: this poll hits OOR, resets, returns nothing yet
    assert cons.poll(10) == []
    assert cons._pending == []
    assert cons._offset is None  # re-resolve on next poll
    assert cons.poll(100) == [f"old-{i}" for i in range(10)]  # replay


def test_consumer_position_excludes_pending(broker):
    """position() reports the DELIVERED offset: records decoded into the
    pending buffer but not yet served must not count (the fetch position
    ``_offset`` runs ahead of the caller by design)."""
    prod = KafkaLiteProducer(broker.address)
    for i in range(10):
        prod.send("pos", str(i))
    prod.flush()
    cons = KafkaLiteConsumer("pos", broker.address)
    got = cons.poll(max_records=3)  # fetch decodes all 10, delivers 3
    assert got == ["0", "1", "2"]
    assert cons.position() == 3
    assert cons._offset == 10  # fetch position ran ahead
    got = cons.poll(max_records=7)
    assert got == [str(i) for i in range(3, 10)]
    assert cons.position() == 10
    prod.close()
    cons.close()


def test_send_blob_roundtrip_and_cap_split():
    """The zero-copy blob produce path must deliver byte-identical records
    to per-record sends, split batches under the request cap, and reject
    single oversized records."""
    import numpy as np
    import pytest

    from skyline_tpu.bridge.kafkalite.broker import Broker
    from skyline_tpu.bridge.kafkalite.client import (
        KafkaLiteConsumer,
        KafkaLiteProducer,
        MessageSizeTooLargeError,
    )

    msgs = [f"{i}," + "v" * (i % 97) for i in range(20000)]
    blob = b"".join(m.encode() for m in msgs)
    offsets = np.zeros(len(msgs) + 1, dtype=np.int64)
    np.cumsum([len(m) for m in msgs], out=offsets[1:])
    with Broker() as b:
        # small cap forces multiple batches through the greedy grouping
        prod = KafkaLiteProducer(b.address, max_request_size=65536)
        prod.send_blob("t", blob, offsets)
        cons = KafkaLiteConsumer("t", b.address, check_crcs=True)
        got, idle = [], 0
        while len(got) < len(msgs) and idle < 50:
            batch = cons.poll(30000)
            idle = 0 if batch else idle + 1
            got.extend(batch)
        assert got == msgs
        # a record BETWEEN (cap - grouping headroom) and cap must still be
        # accepted — the grouping headroom is conservative, the accept/
        # reject decision is the actual encoded batch size
        near = b"y" * 63000
        prod.send_blob("t", near, np.array([0, len(near)], dtype=np.int64))
        got2 = []
        while len(got2) < 1:
            got2.extend(cons.poll(10))
        assert got2[-1] == near.decode()
        big = b"x" * 70000
        with pytest.raises(MessageSizeTooLargeError):
            prod.send_blob(
                "t", big, np.array([0, len(big)], dtype=np.int64)
            )


def test_poll_arrays_matches_line_path(broker):
    """The zero-copy consume plane (native RecordBatch walk + CSV parse)
    delivers exactly what poll() + parse_tuple_lines would, including
    malformed-row drops and offset advance."""
    from skyline_tpu.bridge.wire import parse_tuple_lines
    from skyline_tpu.native import parse_recordbatches_native

    if parse_recordbatches_native(b"", 0, 2) is None:
        pytest.skip("native library unavailable")
    prod = KafkaLiteProducer(broker.address)
    rng = np.random.default_rng(3)
    lines = [
        f"{i},{rng.integers(0, 100)},{rng.integers(0, 100)}"
        for i in range(5000)
    ]
    lines[17] = "badid,1,2"
    lines[4000] = "7,nan,3"
    prod.send_many("pa", lines)
    prod.flush()

    c_lines = KafkaLiteConsumer("pa", broker.address)
    got = []
    for _ in range(30):
        got.extend(c_lines.poll())
        if len(got) >= 5000:
            break
    want_ids, want_vals, want_drop = parse_tuple_lines(got, 2)

    c_arr = KafkaLiteConsumer("pa", broker.address)
    ids = np.empty(0, np.int64)
    vals = np.empty((0, 2), np.float32)
    drop = 0
    for _ in range(30):
        i2, v2, d2 = c_arr.poll_arrays(2)
        ids = np.concatenate([ids, i2])
        vals = np.concatenate([vals, v2])
        drop += d2
        if ids.shape[0] + drop >= 5000:
            break
    np.testing.assert_array_equal(ids, want_ids)
    np.testing.assert_allclose(vals, want_vals)
    assert drop == want_drop == 2
    assert c_arr.position() == c_lines.position() == 5000
    prod.close()
    c_lines.close()
    c_arr.close()


def test_poll_arrays_drains_pending_from_mixed_use(broker):
    """Interleaving poll() (which buffers undelivered decoded records) with
    poll_arrays() must preserve stream order: pending lines drain through
    the parser before any new fetch."""
    from skyline_tpu.native import parse_recordbatches_native

    if parse_recordbatches_native(b"", 0, 1) is None:
        pytest.skip("native library unavailable")
    prod = KafkaLiteProducer(broker.address)
    prod.send_many("mx", [f"{i},{i}" for i in range(500)])
    prod.flush()
    cons = KafkaLiteConsumer("mx", broker.address)
    first = cons.poll(max_records=100)  # leaves 400 pending
    assert len(first) == 100 and cons.position() == 100
    ids, vals, drop = cons.poll_arrays(1)
    # pending (400) delivered first, in order
    assert ids[0] == 100 and ids.shape[0] == 400 and drop == 0
    ids2, _, _ = cons.poll_arrays(1)  # nothing left
    assert ids2.shape[0] == 0
    assert cons.position() == 500
    prod.close()
    cons.close()


def test_poll_degrades_on_non_utf8_like_poll_arrays(broker):
    """A non-UTF-8 value must come through poll() as a replacement-char
    line (dropped as malformed by the downstream parser) instead of
    UnicodeDecodeError killing the consume loop — the line plane degrades
    identically to poll_arrays(), which counts the same record dropped
    (ADVICE.md round 5)."""
    from skyline_tpu.bridge.wire import parse_tuple_lines

    prod = KafkaLiteProducer(broker.address)
    prod.send("u8", "1,10,20")
    prod.send("u8", b"2,\xff\xfe,30")  # invalid UTF-8 inside a value field
    prod.send("u8", "3,40,50")
    prod.flush()

    cons = KafkaLiteConsumer("u8", broker.address)
    got = []
    for _ in range(20):
        got.extend(cons.poll())  # must not raise
        if len(got) >= 3:
            break
    assert got[0] == "1,10,20" and got[2] == "3,40,50"
    assert "�" in got[1]  # degraded, not dropped silently at decode
    ids, _vals, dropped = parse_tuple_lines(got, 2)
    assert list(ids) == [1, 3] and dropped == 1

    # the array plane sees the same shape: two survivors, one drop
    c_arr = KafkaLiteConsumer("u8", broker.address)
    if c_arr.poll_arrays(2) is None:
        pytest.skip("native library unavailable")
    c_arr.close()
    c_arr = KafkaLiteConsumer("u8", broker.address)
    a_ids = []
    a_drop = 0
    for _ in range(20):
        i2, _v2, d2 = c_arr.poll_arrays(2)
        a_ids.extend(i2.tolist())
        a_drop += d2
        if len(a_ids) + a_drop >= 3:
            break
    assert a_ids == [1, 3] and a_drop == 1
    prod.close()
    cons.close()
    c_arr.close()
