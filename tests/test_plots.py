"""Plot tools render PNGs from collector CSVs (headless)."""

import numpy as np

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.metrics.collector import collect
from skyline_tpu.stream import EngineConfig
from skyline_tpu.workload.generators import anti_correlated


def _make_csv(rng, tmp_path, name="run.csv", n=800):
    bus = MemoryBus()
    cfg = EngineConfig(parallelism=2, algo="mr-angle", dims=2,
                       domain_max=10000.0, buffer_size=256,
                       emit_skyline_points=True)
    worker = SkylineWorker(bus, cfg)
    x = anti_correlated(rng, n, 2, 0, 10000)
    bus.produce_many("input-tuples",
                     [format_tuple_line(i, r) for i, r in enumerate(x)])
    bus.produce("queries", format_trigger(0, 0))
    while worker.step() > 0:
        pass
    path = str(tmp_path / name)
    collect(bus.consumer("output-skyline").poll(), path, echo=False)
    return path


def test_skyline_2d_plot(rng, tmp_path):
    from skyline_tpu.plots.skyline_2d import plot_skyline

    csv_path = _make_csv(rng, tmp_path)
    out = plot_skyline(csv_path, out=str(tmp_path / "sky.png"))
    assert (tmp_path / "sky.png").stat().st_size > 0
    assert out.endswith("sky.png")


def test_performance_dashboard(rng, tmp_path):
    from skyline_tpu.plots.performance import plot_performance

    a = _make_csv(rng, tmp_path, "a.csv")
    b = _make_csv(rng, tmp_path, "b.csv", n=600)
    out = plot_performance({"MR-Angle": a, "MR-Grid": b},
                           out=str(tmp_path / "perf.png"))
    assert (tmp_path / "perf.png").stat().st_size > 0


def test_by_dimension_and_paper_figures(rng, tmp_path):
    from skyline_tpu.plots.by_dimension import plot_by_dimension
    from skyline_tpu.plots.paper_figures import plot_paper_figures

    a = _make_csv(rng, tmp_path, "d2.csv")
    out = plot_by_dimension({2: {"MR-Angle": a}}, out=str(tmp_path / "bydim.png"))
    assert (tmp_path / "bydim.png").stat().st_size > 0
    t, o = plot_paper_figures(prefix=str(tmp_path) + "/")
    assert (tmp_path / "figure_5_replication.png").stat().st_size > 0
    assert (tmp_path / "figure_7_replication.png").stat().st_size > 0
