"""Incremental global-merge cache (ISSUE 3 tentpole) semantics.

The epoch-keyed cache must be an invisible optimization: every
``global_merge_stats`` result — exact hit, dirty-subset delta merge, or
full recompute — must be byte-identical to what a cache-off PartitionSet
computes from the same state. These tests pin

* the zero-kernel acceptance criterion: a repeated trigger with no
  intervening flush answers from the cache (``merge.cache_hit`` counter),
* the randomized equivalence property over flush/query interleavings
  across uniform/correlated/anti-correlated workloads and d in {2, 4, 8}
  (d=2 routes through the sweep flush path, whose epoch bump differs),
* the delta path's counters/cutoff knob, and
* the ride-along serving pieces: snapshot publish dedupe by source_key
  and the serve-side read LRU.
"""

import numpy as np
import pytest

from skyline_tpu.metrics.collector import Counters
from skyline_tpu.stream.batched import PartitionSet
from skyline_tpu.workload.generators import anti_correlated, correlated, uniform

# shared state/digest helpers live in conftest.py (satellite of ISSUE 10);
# max_id=0 preserves this file's historical watermark bookkeeping
from conftest import fill_pset, merge_state


def _fill(pset, rng, x, P, max_id=0):
    fill_pset(pset, rng, x, P, max_id=max_id)


def _merge(pset):
    return merge_state(pset)


def test_repeated_trigger_is_pure_cache_hit(rng):
    """ISSUE acceptance: repeated query trigger with no intervening flush
    launches zero merge kernels, observed via the merge.cache_hit
    counter."""
    counters = Counters()
    ps = PartitionSet(4, 4, buffer_size=256, counters=counters)
    _fill(ps, rng, uniform(rng, 2000, 4, 0, 10000).astype(np.float32), 4)

    c1, s1, g1, p1 = _merge(ps)
    assert counters.get("merge.cache_hit") == 0
    assert counters.get("merge.cache_miss") == 1

    c2, s2, g2, p2 = _merge(ps)
    assert counters.get("merge.cache_hit") == 1, "second trigger must not merge"
    assert ps.merge_cache_hits == 1 and ps.merge_cache_misses == 1
    assert g2 == g1 and p2.tobytes() == p1.tobytes()
    np.testing.assert_array_equal(c2, c1)
    np.testing.assert_array_equal(s2, s1)

    # cached results are copies: callers mutating them must not poison
    # subsequent reads
    p2[:] = -1.0
    c2[:] = -1
    _, _, g3, p3 = _merge(ps)
    assert g3 == g1 and p3.tobytes() == p1.tobytes()
    assert counters.get("merge.cache_hit") == 2


def test_flush_invalidates_and_delta_merges(rng, monkeypatch):
    """Dirtying one partition of eight takes the delta path (fraction
    0.125 <= cutoff) and matches the cache-off full recompute."""
    P = 8
    ps = PartitionSet(P, 4, buffer_size=512)
    ref = PartitionSet(P, 4, buffer_size=512)
    monkeypatch.delenv("SKYLINE_MERGE_CACHE", raising=False)
    x = anti_correlated(rng, 4000, 4, 0, 10000).astype(np.float32)
    r2 = np.random.default_rng(0)
    _fill(ps, r2, x, P)
    r2 = np.random.default_rng(0)
    _fill(ref, r2, x, P)
    _merge(ps)  # prime the cache

    top = uniform(rng, 64, 4, 0, 10000).astype(np.float32)
    for t in (ps, ref):
        t.add_batch(0, top, max_id=1, now_ms=0.0)
        t.flush_all()

    res = _merge(ps)
    assert ps.merge_delta_merges == 1
    assert ps.last_dirty_fraction == pytest.approx(1 / P)
    assert ps.merge_delta_rows > 0

    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    want = _merge(ref)
    assert res[2] == want[2]
    assert res[3].tobytes() == want[3].tobytes()
    np.testing.assert_array_equal(res[0], want[0])
    np.testing.assert_array_equal(res[1], want[1])


def test_delta_cutoff_zero_disables_delta_path(rng, monkeypatch):
    """SKYLINE_DELTA_CUTOFF=0 keeps the exact-hit cache but forces full
    merges for any dirty state."""
    monkeypatch.setenv("SKYLINE_DELTA_CUTOFF", "0")
    ps = PartitionSet(4, 3, buffer_size=256)
    _fill(ps, rng, uniform(rng, 1000, 3, 0, 10000).astype(np.float32), 4)
    _merge(ps)
    ps.add_batch(0, uniform(rng, 16, 3, 0, 10000).astype(np.float32),
                 max_id=1, now_ms=0.0)
    ps.flush_all()
    _merge(ps)
    assert ps.merge_delta_merges == 0
    assert ps.merge_cache_misses == 2
    _merge(ps)
    assert ps.merge_cache_hits == 1  # exact-hit reuse still works


@pytest.mark.parametrize("gen", [uniform, correlated, anti_correlated],
                         ids=["uniform", "correlated", "anti_correlated"])
@pytest.mark.parametrize("d", [2, 4, 8])
def test_equivalence_random_interleaving(gen, d, monkeypatch):
    """Property: under random flush/query interleavings the cached engine's
    every answer is byte-identical to a cache-off twin fed the same
    batches (counts, survivors, global count, and point bytes)."""
    P = 4
    rng = np.random.default_rng(d * 101 + len(gen.__name__))
    cached = PartitionSet(P, d, buffer_size=256)
    plain = PartitionSet(P, d, buffer_size=256)

    def trigger_both():
        monkeypatch.setenv("SKYLINE_MERGE_CACHE", "1")
        a = _merge(cached)
        monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
        b = _merge(plain)
        assert a[2] == b[2], "global count diverged"
        assert a[3].tobytes() == b[3].tobytes(), "points diverged"
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])

    # seed both with identically-routed batches, then interleave
    x0 = gen(rng, 1200, d, 0, 10000).astype(np.float32)
    pids0 = rng.integers(0, P, x0.shape[0])
    for t in (cached, plain):
        for p in range(P):
            rows = np.ascontiguousarray(x0[pids0 == p])
            if rows.shape[0]:
                t.add_batch(p, rows, max_id=0, now_ms=0.0)
        t.flush_all()
    for step in range(10):
        op = rng.integers(0, 3)
        if step == 0 or op == 0:
            # dirty a random non-empty subset of partitions
            k = int(rng.integers(1, P + 1))
            parts = rng.choice(P, size=k, replace=False)
            for p in parts:
                rows = gen(rng, int(rng.integers(1, 400)), d, 0, 10000)
                rows = rows.astype(np.float32)
                for t in (cached, plain):
                    t.add_batch(int(p), rows.copy(), max_id=step, now_ms=0.0)
            for t in (cached, plain):
                t.flush_all()
            trigger_both()
        elif op == 1:
            trigger_both()  # repeated trigger: exact-hit path
        else:
            # flush with no new rows then trigger (epoch must not churn
            # into spurious misses, and must not miss real changes)
            for t in (cached, plain):
                t.flush_all()
            trigger_both()


def test_equivalence_with_staging_disabled(rng, monkeypatch):
    """SKYLINE_STAGE_DEPTH=0 (synchronous flushes) must not change any
    merged bytes."""
    monkeypatch.setenv("SKYLINE_STAGE_DEPTH", "0")
    P, d = 4, 4
    cached = PartitionSet(P, d, buffer_size=256)
    plain = PartitionSet(P, d, buffer_size=256)
    x = anti_correlated(rng, 3000, d, 0, 10000).astype(np.float32)
    for t, seed in ((cached, 3), (plain, 3)):
        _fill(t, np.random.default_rng(seed), x, P)
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "1")
    a = _merge(cached)
    a2 = _merge(cached)  # hit
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    b = _merge(plain)
    assert a[3].tobytes() == b[3].tobytes() == a2[3].tobytes()
    assert cached.merge_cache_hits == 1


def test_restore_drops_cache(rng):
    """restore_all must invalidate: a stale cached global would resurrect
    pre-restore state."""
    ps = PartitionSet(2, 3, buffer_size=128)
    x = uniform(rng, 500, 3, 0, 10000).astype(np.float32)
    _fill(ps, rng, x, 2)
    _merge(ps)
    skies = [ps.skyline_host(p) for p in range(2)]
    pendings = [ps.pending_rows_of(p) for p in range(2)]
    y = uniform(rng, 500, 3, 0, 10000).astype(np.float32) + 20000
    ps.restore_all(skies, pendings)  # epoch bumped, cache dropped
    before = _merge(ps)
    ps.add_batch(0, y, max_id=1, now_ms=0.0)
    ps.flush_all()
    after = _merge(ps)
    assert ps.merge_cache_hits == 0  # every post-restore state was new
    assert before[2] >= 1 and after[2] >= 1


def test_snapshot_store_dedupes_by_source_key():
    from skyline_tpu.serve.snapshot import SnapshotStore

    store = SnapshotStore()
    pts = np.arange(6, dtype=np.float32).reshape(3, 2)
    s1 = store.publish(pts, watermark_id=0, source_key=b"k1")
    s2 = store.publish(pts, watermark_id=1, source_key=b"k1")
    assert s2 is s1 and s2.version == s1.version
    assert store.stats()["deduped"] == 1
    s3 = store.publish(pts, watermark_id=2, source_key=b"k2")
    assert s3.version == s1.version + 1
    # un-keyed publishes never dedupe
    s4 = store.publish(pts, watermark_id=3)
    assert s4.version == s3.version + 1
    assert store.stats()["deduped"] == 1
