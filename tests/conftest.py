"""Test harness: force an 8-virtual-device CPU platform BEFORE jax imports.

Multi-chip TPU hardware is not available in CI; per SURVEY.md §4 item 5 the
reference simulates distribution with a local Flink mini-cluster — our
equivalent is XLA's host-platform device-count override, which exercises the
full shard_map/collective path on 8 virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU plugin (axon) imports jax at interpreter startup, so
# the env vars above can be too late; the backend itself is still
# uninitialized at conftest time, so a config update takes effect.
import jax

jax.config.update("jax_platforms", "cpu")

import warnings

# ops.sfs jits donate their sky buffers (in-place append rounds on TPU);
# the CPU backend does not implement donation and warns per compile
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def sorted_rows(a):
    """Canonical row order for comparing point sets as multisets."""
    a = np.asarray(a, dtype=np.float64)
    return a[np.lexsort(a.T[::-1])]


def assert_same_set(a, b):
    np.testing.assert_allclose(sorted_rows(a), sorted_rows(b))


def gen_points(rng, n, d, kind) -> np.ndarray:
    """Shared workload shapes for the byte-identity property grids
    (uniform / correlated / anti-correlated), float32 in [0, 1]."""
    if kind == "uniform":
        return rng.random((n, d)).astype(np.float32)
    if kind == "correlated":
        base = rng.random((n, 1))
        return np.clip(
            base + rng.normal(0.0, 0.05, (n, d)), 0.0, 1.0
        ).astype(np.float32)
    # anti-correlated: first dim fights the second, rest random
    base = rng.random((n, d))
    x = base.copy()
    x[:, 0] = 1.0 - base[:, min(1, d - 1)]
    return x.astype(np.float32)


def fill_pset(pset, rng, x, P, max_id=None) -> None:
    """Route ``x`` across ``P`` partitions at random and flush once — the
    shared per-test state builder."""
    if max_id is None:
        max_id = x.shape[0]
    pids = rng.integers(0, P, x.shape[0])
    for p in range(P):
        rows = np.ascontiguousarray(x[pids == p])
        if rows.shape[0]:
            pset.add_batch(p, rows, max_id=max_id, now_ms=0.0)
    pset.flush_all()


def merge_state(pset):
    """One global merge with points: (counts, survivors, global_count,
    points) as host arrays — the digest the identity asserts compare."""
    counts, surv, g, pts = pset.global_merge_stats(emit_points=True)
    return np.asarray(counts), np.asarray(surv), int(g), np.asarray(pts)


def assert_same_merge(a, b, ctx="") -> None:
    """Byte-identity of two ``merge_state`` results (order included)."""
    assert (a[0] == b[0]).all(), f"counts diverge {ctx}"
    assert (a[1] == b[1]).all(), f"survivors diverge {ctx}"
    assert a[2] == b[2], f"global count diverges {ctx}"
    assert a[3].tobytes() == b[3].tobytes(), f"points diverge {ctx}"


def host_oracle(rows) -> np.ndarray:
    """The independent O(n^2 d) numpy skyline oracle, rows in canonical
    order as float32 — what the audit plane compares published answers
    against (skyline_tpu/audit)."""
    from skyline_tpu.audit import canonical_rows
    from skyline_tpu.ops.dominance import skyline_np

    rows = np.asarray(rows, dtype=np.float32)
    if rows.shape[0] == 0:
        return rows
    return canonical_rows(np.asarray(skyline_np(rows), dtype=np.float32))


def points_digest_of(points) -> str:
    """Digest of a point buffer under the serve plane's scheme — lets
    tests compare engine output to a published snapshot's ``digest``."""
    from skyline_tpu.serve.snapshot import points_digest

    return points_digest(
        np.ascontiguousarray(np.asarray(points, dtype=np.float32))
    )


def parse_prometheus_text(text: str) -> dict:
    """Minimal Prometheus text-exposition (0.0.4) parser for assertions.

    Returns ``{metric_name: [(labels_dict, float_value), ...]}`` and
    raises AssertionError on any malformed line — the tests' contract
    that /metrics stays scrapeable. Handles ``# TYPE``/``# HELP``
    comments, label sets, and ``+Inf``/``-Inf``/``NaN`` values.
    """
    series: dict = {}
    types: dict = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            assert len(parts) >= 3 and parts[1] in ("TYPE", "HELP"), (
                f"malformed comment line: {raw!r}"
            )
            if parts[1] == "TYPE":
                assert parts[3] in (
                    "counter", "gauge", "histogram", "summary", "untyped"
                ), f"bad TYPE: {raw!r}"
                types[parts[2]] = parts[3]
            continue
        head, _, val = line.rpartition(" ")
        assert head, f"malformed sample line: {raw!r}"
        labels: dict = {}
        if "{" in head:
            name, _, rest = head.partition("{")
            assert rest.endswith("}"), f"malformed labels: {raw!r}"
            for pair in filter(None, rest[:-1].split(",")):
                k, _, v = pair.partition("=")
                assert v.startswith('"') and v.endswith('"'), (
                    f"unquoted label value: {raw!r}"
                )
                labels[k] = v[1:-1]
        else:
            name = head
        assert name and name[0] not in "0123456789", f"bad name: {raw!r}"
        assert all(
            c.isalnum() or c in "_:" for c in name
        ), f"bad metric name char: {raw!r}"
        series.setdefault(name, []).append((labels, float(val)))
    series["__types__"] = types
    return series


@pytest.fixture
def prom_parse():
    return parse_prometheus_text
