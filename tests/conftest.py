"""Test harness: force an 8-virtual-device CPU platform BEFORE jax imports.

Multi-chip TPU hardware is not available in CI; per SURVEY.md §4 item 5 the
reference simulates distribution with a local Flink mini-cluster — our
equivalent is XLA's host-platform device-count override, which exercises the
full shard_map/collective path on 8 virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU plugin (axon) imports jax at interpreter startup, so
# the env vars above can be too late; the backend itself is still
# uninitialized at conftest time, so a config update takes effect.
import jax

jax.config.update("jax_platforms", "cpu")

import warnings

# ops.sfs jits donate their sky buffers (in-place append rounds on TPU);
# the CPU backend does not implement donation and warns per compile
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def sorted_rows(a):
    """Canonical row order for comparing point sets as multisets."""
    a = np.asarray(a, dtype=np.float64)
    return a[np.lexsort(a.T[::-1])]


def assert_same_set(a, b):
    np.testing.assert_allclose(sorted_rows(a), sorted_rows(b))


def parse_prometheus_text(text: str) -> dict:
    """Minimal Prometheus text-exposition (0.0.4) parser for assertions.

    Returns ``{metric_name: [(labels_dict, float_value), ...]}`` and
    raises AssertionError on any malformed line — the tests' contract
    that /metrics stays scrapeable. Handles ``# TYPE``/``# HELP``
    comments, label sets, and ``+Inf``/``-Inf``/``NaN`` values.
    """
    series: dict = {}
    types: dict = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            assert len(parts) >= 3 and parts[1] in ("TYPE", "HELP"), (
                f"malformed comment line: {raw!r}"
            )
            if parts[1] == "TYPE":
                assert parts[3] in (
                    "counter", "gauge", "histogram", "summary", "untyped"
                ), f"bad TYPE: {raw!r}"
                types[parts[2]] = parts[3]
            continue
        head, _, val = line.rpartition(" ")
        assert head, f"malformed sample line: {raw!r}"
        labels: dict = {}
        if "{" in head:
            name, _, rest = head.partition("{")
            assert rest.endswith("}"), f"malformed labels: {raw!r}"
            for pair in filter(None, rest[:-1].split(",")):
                k, _, v = pair.partition("=")
                assert v.startswith('"') and v.endswith('"'), (
                    f"unquoted label value: {raw!r}"
                )
                labels[k] = v[1:-1]
        else:
            name = head
        assert name and name[0] not in "0123456789", f"bad name: {raw!r}"
        assert all(
            c.isalnum() or c in "_:" for c in name
        ), f"bad metric name char: {raw!r}"
        series.setdefault(name, []).append((labels, float(val)))
    series["__types__"] = types
    return series


@pytest.fixture
def prom_parse():
    return parse_prometheus_text
