"""Test harness: force an 8-virtual-device CPU platform BEFORE jax imports.

Multi-chip TPU hardware is not available in CI; per SURVEY.md §4 item 5 the
reference simulates distribution with a local Flink mini-cluster — our
equivalent is XLA's host-platform device-count override, which exercises the
full shard_map/collective path on 8 virtual devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The environment's TPU plugin (axon) imports jax at interpreter startup, so
# the env vars above can be too late; the backend itself is still
# uninitialized at conftest time, so a config update takes effect.
import jax

jax.config.update("jax_platforms", "cpu")

import warnings

# ops.sfs jits donate their sky buffers (in-place append rounds on TPU);
# the CPU backend does not implement donation and warns per compile
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def sorted_rows(a):
    """Canonical row order for comparing point sets as multisets."""
    a = np.asarray(a, dtype=np.float64)
    return a[np.lexsort(a.T[::-1])]


def assert_same_set(a, b):
    np.testing.assert_allclose(sorted_rows(a), sorted_rows(b))
