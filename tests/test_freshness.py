"""Freshness lineage, kernel profiler, flight recorder, and SLO engine
unit tests (ISSUE 8): stage-window watermark flow, labeled Prometheus
families, staleness fallback on reads, burn-rate windows with an injected
clock, and the end-to-end engine lineage on a real query."""

import numpy as np
import pytest

from skyline_tpu.serve import SnapshotStore
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine
from skyline_tpu.telemetry import (
    FlightRecorder,
    FreshnessTracker,
    KernelProfiler,
    SloEngine,
    Telemetry,
)
from skyline_tpu.telemetry.profiler import n_bucket


# --------------------------------------------------------- freshness tracker


def _counts(fr):
    return {s: h.count for s, h in fr._hists.items()}


def test_tracker_stage_flow_and_watermark():
    fr = FreshnessTracker()
    # two batches land, then the cascade drains: flush lag is measured from
    # the OLDEST waiting event-time, and the published watermark is the
    # newest event-time that reached the snapshot
    fr.on_ingest(1000.0, 1500.0, now_ms=1600.0)
    fr.on_ingest(1200.0, 2000.0, now_ms=2100.0)
    fr.on_flush(now_ms=3000.0)
    fr.on_merge(now_ms=4000.0)
    wm = fr.on_publish(now_ms=5000.0)
    assert wm == 2000.0
    assert _counts(fr) == {
        "ingest": 2, "flush": 1, "merge": 1, "publish": 1, "read": 0,
    }
    # lag at each transition = now - oldest waiting event-time
    assert fr._hists["flush"].quantile(1.0) == pytest.approx(2000.0)
    assert fr._hists["merge"].quantile(1.0) == pytest.approx(3000.0)
    assert fr._hists["publish"].quantile(1.0) == pytest.approx(4000.0)
    st = fr.stats()
    assert st["batches"] == 2
    assert st["published_wm_ms"] == 2000.0


def test_tracker_empty_transitions_are_idempotent():
    fr = FreshnessTracker()
    # nothing pending: flush/merge/publish record no samples and the
    # watermark stays unset
    fr.on_flush(now_ms=10.0)
    fr.on_merge(now_ms=20.0)
    assert fr.on_publish(now_ms=30.0) is None
    assert _counts(fr) == {
        "ingest": 0, "flush": 0, "merge": 0, "publish": 0, "read": 0,
    }
    # a second flush after the window drained records nothing either
    fr.on_ingest(100.0, 100.0, now_ms=100.0)
    fr.on_flush(now_ms=110.0)
    fr.on_flush(now_ms=120.0)
    assert _counts(fr)["flush"] == 1


def test_tracker_watermark_monotone_and_restore():
    fr = FreshnessTracker()
    fr.on_ingest(0.0, 5000.0, now_ms=5000.0)
    fr.on_flush(now_ms=5001.0)
    fr.on_merge(now_ms=5002.0)
    assert fr.on_publish(now_ms=5003.0) == 5000.0
    # an older batch flowing later must not move the watermark backwards
    fr.on_ingest(100.0, 200.0, now_ms=5100.0)
    fr.on_flush(now_ms=5101.0)
    fr.on_merge(now_ms=5102.0)
    assert fr.on_publish(now_ms=5103.0) == 5000.0
    # restore is monotone-max too: a stale checkpoint can't regress it
    fr.restore(4000.0)
    assert fr.stats()["published_wm_ms"] == 5000.0
    fr.restore(9000.0)
    assert fr.stats()["published_wm_ms"] == 9000.0
    fr.restore(None)  # no-op
    assert fr.stats()["published_wm_ms"] == 9000.0


def test_tracker_registers_on_hub_and_renders_labeled(prom_parse):
    tel = Telemetry()
    fr = FreshnessTracker(tel)
    fr.on_ingest(1000.0, 1000.0, now_ms=1250.0)
    fr.on_read(42.0)
    series = prom_parse(tel.render_prometheus())
    types = series.pop("__types__")
    assert types["skyline_freshness_lag_ms"] == "histogram"
    buckets = series["skyline_freshness_lag_ms_bucket"]
    stages = {lbl["stage"] for lbl, _ in buckets}
    assert stages == {"ingest", "flush", "merge", "publish", "read"}
    # per-series cumulative counts: ingest saw one 250ms lag, read one 42ms
    counts = {
        lbl["stage"]: v
        for lbl, v in series["skyline_freshness_lag_ms_count"]
    }
    assert counts["ingest"] == 1.0 and counts["read"] == 1.0
    assert counts["flush"] == 0.0
    read_lag = fr.stats()["read_lag_p99_ms"]
    assert read_lag == pytest.approx(42.0)


# ------------------------------------------------- snapshot-store staleness


def test_snapshot_store_staleness_and_fallback():
    store = SnapshotStore()
    pts = np.zeros((3, 2), dtype=np.float32)
    # no event watermark anywhere: staleness falls back to snapshot age
    store.publish(pts, query_id="q")
    rs = store.read()
    assert rs.staleness_ms == rs.age_ms
    # an event-stamped publish: staleness is measured from the watermark
    store.note_ingest(event_ms=123.0)
    store.publish(np.ones((3, 2), dtype=np.float32), query_id="q")
    snap = store.latest()
    assert snap.event_wm_ms == 123.0
    assert snap.to_doc()["event_wm_ms"] == 123.0
    rs = store.read()
    assert rs.staleness_ms > rs.age_ms  # epoch 123ms is ancient
    assert store.stats()["published_event_wm_ms"] == 123.0


def test_snapshot_store_restore_keeps_watermark():
    store = SnapshotStore()
    pts = np.zeros((2, 2), dtype=np.float32)
    store.restore_state(pts, version=7, watermark_id=10, event_wm_ms=555.0)
    assert store.latest().event_wm_ms == 555.0
    assert store.stats()["event_watermark_ms"] == 555.0
    # a later publish with no fresh stamp inherits the restored watermark
    store.publish(np.ones((2, 2), dtype=np.float32), query_id="q")
    assert store.latest().event_wm_ms == 555.0


# ------------------------------------------------------------ kernel profiler


def test_n_bucket_powers_of_two():
    assert [n_bucket(n) for n in (0, 1, 2, 3, 5, 64, 65)] == [
        0, 1, 2, 4, 8, 64, 128,
    ]


def test_profiler_signatures_and_retrace_canary():
    prof = KernelProfiler(backend="testbk")
    for n in (100, 120, 300):  # 128-bucket x2, 512-bucket x1
        with prof.record("merge_step", 4, n):
            pass
    doc = prof.doc()
    assert doc["signatures"] == 2 and doc["dispatches"] == 3
    by_bucket = {r["n_bucket"]: r for r in doc["kernels"]}
    assert by_bucket[128]["calls"] == 2
    assert by_bucket[512]["calls"] == 1
    # first_call_ms (the retrace canary) is pinned at the first dispatch
    assert by_bucket[128]["first_call_ms"] is not None
    assert doc["retraces_per_variant"] == {"merge_step": 2}
    # attribution: the profiler timed everything the phase saw (use the
    # unrounded total — the doc's is rounded to 3 decimals and these empty
    # dispatches take microseconds)
    doc = prof.doc(phase_total_ms=prof.total_wall_ms())
    assert doc["attributed_share"] == pytest.approx(1.0, rel=1e-3)


def test_profiler_cost_thunk_once_and_defensive():
    calls = []

    def thunk():
        calls.append(1)
        return [{"flops": 10.0, "bytes accessed": 20.0}]  # older-jaxlib shape

    prof = KernelProfiler(backend="testbk")
    for _ in range(3):
        with prof.record("v", 2, 8, cost_thunk=thunk):
            pass
    assert len(calls) == 1  # AOT cost runs once per signature
    (row,) = prof.doc()["kernels"]
    assert row["cost"] == {"flops": 10.0, "bytes_accessed": 20.0}

    def broken():
        raise RuntimeError("no cost on this backend")

    with prof.record("v2", 2, 8, cost_thunk=broken):
        pass  # must not raise
    rows = {r["variant"]: r for r in prof.doc()["kernels"]}
    assert "cost" not in rows["v2"]


# ------------------------------------------------------------ flight recorder


def test_flight_recorder_bounded_and_partial():
    fl = FlightRecorder(capacity=4)
    for i in range(10):
        fl.note("merge.launch", path="flat", i=i)
    doc = fl.doc()
    assert len(doc["entries"]) == 4
    assert doc["recorded_total"] == 10 and doc["partial"] is True
    assert [e["i"] for e in doc["entries"]] == [6, 7, 8, 9]
    assert doc["entries"][-1]["seq"] == 10


def test_flight_recorder_dump_json_line():
    import io
    import json

    fl = FlightRecorder(capacity=8)
    fl.note("flush.dispatch", rows=5)
    buf = io.StringIO()
    fl.dump("crash: InjectedCrash: boom", stream=buf)
    line = buf.getvalue().strip()
    assert line.startswith("skyline-flight-recorder: ")
    doc = json.loads(line.split(": ", 1)[1])
    assert doc["reason"].startswith("crash:")
    assert doc["entries"][0]["kind"] == "flush.dispatch"


# ----------------------------------------------------------------- SLO engine


def test_slo_engine_healthy_and_breach():
    tel = Telemetry()
    t = {"now": 0.0}
    slo = SloEngine(tel, clock=lambda: t["now"])
    # healthy: reads well under the 50ms target
    for _ in range(100):
        tel.histogram("serve_read_ms").observe(1.0)
    doc = slo.evaluate()
    assert doc["ok"] is True
    assert set(doc["slos"]) == {
        "read_p99", "freshness_p99", "shed_fraction", "restart_rate",
        "audit_divergence", "degraded_answers", "tenant_shed_fraction",
        "replication_lag_p99", "promote_p99",
    }
    # now every read blows the target: burn must exceed 1 on BOTH windows
    t["now"] = 30.0
    for _ in range(400):
        tel.histogram("serve_read_ms").observe(5000.0)
    t["now"] = 60.0
    doc = slo.evaluate()
    read = doc["slos"]["read_p99"]
    assert read["breach"] is True and doc["ok"] is False
    for w in ("fast", "slow"):
        assert read["windows"][w]["burn_rate"] > 1.0
    # the untouched SLOs stay green
    assert doc["slos"]["shed_fraction"]["breach"] is False
    assert doc["slos"]["restart_rate"]["breach"] is False


def test_slo_restart_rate_uses_counter():
    tel = Telemetry()
    t = {"now": 0.0}
    slo = SloEngine(tel, clock=lambda: t["now"])
    slo.evaluate()
    # 6/h allowed; 30 restarts in 10 minutes is a 30x burn on the fast
    # window and (cold slow window -> same span) the slow one too
    for _ in range(30):
        tel.inc("resilience.restarts")
    t["now"] = 600.0
    doc = slo.evaluate()
    rr = doc["slos"]["restart_rate"]
    assert rr["breach"] is True
    assert rr["windows"]["fast"]["events"] == 30


# --------------------------------------------------- engine lineage e2e (cpu)


def _run_query(tel, event_ms=None):
    # dims=3: the 2-D fast path bypasses the profiled kernel dispatch sites
    eng = SkylineEngine(EngineConfig(parallelism=2, dims=3), telemetry=tel)
    store = SnapshotStore()
    eng.attach_snapshots(store)
    rng = np.random.default_rng(0)
    ids = np.arange(1, 301, dtype=np.int64)
    vals = rng.uniform(1, 999, size=(300, 3)).astype(np.float32)
    eng.process_records(ids, vals, event_ms=event_ms)
    eng.process_trigger("q1,0")
    (result,) = eng.poll_results()
    return eng, store, result


def test_engine_lineage_end_to_end():
    tel = Telemetry()
    eng, store, result = _run_query(tel, event_ms=(1000.0, 2000.0))
    fr = eng.stats()["freshness"]
    for stage in ("ingest", "flush", "merge", "publish"):
        assert fr["stages"][stage]["count"] >= 1, (stage, fr)
    assert fr["published_wm_ms"] == 2000.0
    assert store.latest().event_wm_ms == 2000.0
    # the store-level read computes staleness from the published watermark
    rs = store.read()
    assert rs.staleness_ms is not None and rs.staleness_ms > 0


def test_engine_profile_registry_populated():
    tel = Telemetry()
    eng, _, _ = _run_query(tel)
    kp = eng.stats()["kernel_profile"]
    assert kp["signatures"] >= 1 and kp["dispatches"] >= 1
    assert any(r["calls"] >= 1 for r in kp["kernels"])
    # the same registry serves /profile via the shared hub
    assert tel.profiler.doc()["signatures"] == kp["signatures"]


def test_engine_freshness_off_leaves_stats_clean(monkeypatch):
    monkeypatch.setenv("SKYLINE_FRESHNESS", "0")
    monkeypatch.setenv("SKYLINE_KERNEL_PROFILE", "0")
    eng, store, result = _run_query(None)
    st = eng.stats()
    assert "freshness" not in st and "kernel_profile" not in st
    # no tracker -> no event stamp anywhere; reads fall back to age
    assert store.latest().event_wm_ms is None
    rs = store.read()
    assert rs.staleness_ms == rs.age_ms
    assert result["skyline_size"] > 0
