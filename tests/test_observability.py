"""Observability integration tests: the StatsServer surface (/healthz,
/stats, dashboard, /metrics, /trace), the serving plane's /metrics, the
worker's --trace-out Chrome trace file, and the bench_compare gate."""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.metrics.httpstats import StatsServer
from skyline_tpu.telemetry import Telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


# -------------------------------------------------------------- StatsServer


def test_statsserver_healthz():
    srv = StatsServer(lambda: {"x": 1}, port=0)
    try:
        status, _, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert status == 200
        assert json.loads(body) == {"ok": True}
    finally:
        srv.close()


def test_statsserver_stats_500_on_callback_exception():
    def boom():
        raise RuntimeError("stats backend unavailable")

    srv = StatsServer(boom, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/stats")
        assert ei.value.code == 500
        assert "stats backend unavailable" in json.load(ei.value)["error"]
        # /metrics flattens the same callback — same contract
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert ei.value.code == 500
    finally:
        srv.close()


def test_statsserver_dashboard_html():
    srv = StatsServer(lambda: {"records_in": 5}, port=0)
    try:
        status, ctype, body = _get(f"http://127.0.0.1:{srv.port}/")
        assert status == 200 and "text/html" in ctype
        html = body.decode()
        assert "tpu-skyline worker" in html
        assert "/stats" in html
        # the serve-plane and latency tile blocks ship with the page
        assert "serving plane" in html
        assert "p50 / p99" in html
        assert "reads shed (429)" in html
    finally:
        srv.close()


def test_statsserver_metrics_prometheus(prom_parse):
    tel = Telemetry()
    tel.histogram("query_latency_ms").observe_many([1.0, 5.0, 20.0])
    tel.counters.inc("results_total", 3)
    stats = {
        "records_in": 1000,
        "nested": {"depth": 2},
        "latency_ms": tel.latency_snapshot(),  # must not double-export
        "label": "text",  # non-numeric: dropped from gauges
    }
    srv = StatsServer(lambda: stats, port=0, telemetry=tel)
    try:
        status, ctype, body = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert status == 200
        assert "version=0.0.4" in ctype
        series = prom_parse(body.decode())
        types = series.pop("__types__")
        assert series["skyline_records_in"] == [({}, 1000.0)]
        assert series["skyline_nested_depth"] == [({}, 2.0)]
        assert series["skyline_results_total_total"] == [({}, 3.0)]
        assert types["skyline_query_latency_ms"] == "histogram"
        assert series["skyline_query_latency_ms_count"] == [({}, 3.0)]
        buckets = series["skyline_query_latency_ms_bucket"]
        assert buckets[-1][0] == {"le": "+Inf"}
        # latency_ms summaries must not leak in as gauges next to the
        # real histogram series
        assert not any("latency_ms_p50" in k for k in series)
    finally:
        srv.close()


def test_statsserver_metrics_without_telemetry(prom_parse):
    srv = StatsServer(lambda: {"records_in": 7}, port=0)
    try:
        _, _, body = _get(f"http://127.0.0.1:{srv.port}/metrics")
        series = prom_parse(body.decode())
        assert series["skyline_records_in"] == [({}, 7.0)]
    finally:
        srv.close()


def test_statsserver_trace_endpoint():
    tel = Telemetry()
    with tel.spans.span("unit", trace_id="t-9"):
        pass
    srv = StatsServer(lambda: {}, port=0, telemetry=tel)
    try:
        _, _, body = _get(f"http://127.0.0.1:{srv.port}/trace")
        doc = json.loads(body)
        assert doc["traceEvents"][0]["name"] == "unit"
        assert doc["traceEvents"][0]["args"]["trace_id"] == "t-9"
    finally:
        srv.close()
    # without a hub the endpoint still answers with an empty trace
    srv = StatsServer(lambda: {}, port=0)
    try:
        _, _, body = _get(f"http://127.0.0.1:{srv.port}/trace")
        assert json.loads(body) == {"traceEvents": []}
    finally:
        srv.close()


# ------------------------------------------------- worker + serving plane


@pytest.fixture
def traced_worker(tmp_path):
    from skyline_tpu.stream.engine import EngineConfig

    trace_out = str(tmp_path / "trace.json")
    bus = MemoryBus()
    worker = SkylineWorker(
        bus,
        EngineConfig(parallelism=2, dims=3),
        stats_port=0,
        serve_port=0,
        trace_out=trace_out,
    )
    rng = np.random.default_rng(2)
    x = rng.uniform(1, 9999, size=(2000, 3)).astype(np.float32)
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(i, row) for i, row in enumerate(x)],
    )
    bus.produce("queries", format_trigger(0, 0))
    while worker.step() > 0:
        pass
    try:
        yield worker, trace_out
    finally:
        worker.close()


def test_serve_server_metrics_prometheus(traced_worker, prom_parse):
    worker, _ = traced_worker
    base = f"http://127.0.0.1:{worker.serve_server.port}"
    # one admitted read so serve counters and serve_read_ms move
    status, _, _ = _get(f"{base}/skyline")
    assert status == 200
    status, ctype, body = _get(f"{base}/metrics")
    assert status == 200
    assert "version=0.0.4" in ctype
    series = prom_parse(body.decode())
    series.pop("__types__")
    assert series["skyline_serve_reads_admitted_total"][0][1] >= 1.0
    assert series["skyline_snapshot_store_head_version"] == [({}, 1.0)]
    assert "skyline_serve_read_ms_bucket" in series
    assert series["skyline_serve_bridge_depth"] == [({}, 0.0)]


def test_worker_stats_latency_section(traced_worker):
    worker, _ = traced_worker
    stats = worker.stats()
    lat = stats["latency_ms"]
    for name in ("ingest_batch_ms", "global_merge_ms", "query_latency_ms"):
        assert lat[name]["count"] >= 1, (name, lat)
        assert lat[name]["p50"] <= lat[name]["p99"]


def test_worker_trace_out_chrome_schema(traced_worker):
    # acceptance: a captured --trace-out file validates against the Chrome
    # trace-event schema and contains the spans of one query's life:
    # ingest -> local -> merge -> publish (serve plane attached)
    worker, trace_out = traced_worker
    worker.close()
    with open(trace_out) as f:
        doc = json.loads(f.read())
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    for e in doc["traceEvents"]:
        assert e["ph"] == "X"
        assert isinstance(e["name"], str)
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    by_name = {}
    for e in doc["traceEvents"]:
        by_name.setdefault(e["name"], []).append(e)
    for phase in ("ingest", "local", "merge", "publish"):
        assert phase in by_name, (phase, sorted(by_name))
    # local/merge/publish of the same query share its trace_id
    tid = by_name["merge"][0]["args"]["trace_id"]
    assert tid
    assert by_name["publish"][0]["args"]["trace_id"] == tid
    assert any(
        e["args"].get("trace_id") == tid for e in by_name["local"]
    )


def test_worker_metrics_on_stats_server(traced_worker, prom_parse):
    worker, _ = traced_worker
    base = f"http://127.0.0.1:{worker.stats_server.port}"
    _, _, body = _get(f"{base}/metrics")
    series = prom_parse(body.decode())
    series.pop("__types__")
    assert "skyline_ingest_batch_ms_bucket" in series
    assert series["skyline_query_latency_ms_count"][0][1] >= 1.0
    assert series["skyline_results_emitted"] == [({}, 1.0)]


# ------------------------------------------------------------ bench gate


def _run_compare(args, cwd):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py")]
        + args,
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=60,
    )


def _bench_doc(value, p50, backend="cpu-fallback"):
    return {
        "n": 1,
        "rc": 0,
        "parsed": {
            "value": value,
            "backend": backend,
            "p50_window_latency_ms": p50,
            "serve": {"read_p50_ms": 1.0, "read_p99_ms": 5.0},
        },
    }


def test_bench_compare_ok_and_regression(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(_bench_doc(1000.0, 500.0))
    )
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps(_bench_doc(1050.0, 480.0))
    )
    res = _run_compare(["--dir", str(tmp_path)], cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "ok" in res.stdout
    # >25% throughput drop trips the gate
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps(_bench_doc(500.0, 480.0))
    )
    res = _run_compare(["--dir", str(tmp_path)], cwd=str(tmp_path))
    assert res.returncode == 1
    assert "REGRESSION" in res.stdout + res.stderr


def test_bench_compare_latency_regression_and_threshold(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_doc(1000.0, 500.0)))
    new.write_text(json.dumps(_bench_doc(1000.0, 600.0)))  # +20% p50
    res = _run_compare([str(old), str(new)], cwd=str(tmp_path))
    assert res.returncode == 0  # within default 25%
    res = _run_compare(
        [str(old), str(new), "--threshold", "0.10"], cwd=str(tmp_path)
    )
    assert res.returncode == 1


def test_bench_compare_backend_mismatch_passes(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(_bench_doc(9000.0, 100.0, backend="tpu")))
    new.write_text(json.dumps(_bench_doc(900.0, 1000.0)))
    res = _run_compare([str(old), str(new)], cwd=str(tmp_path))
    assert res.returncode == 0
    assert "incomparable" in res.stdout


def test_bench_compare_too_few_artifacts(tmp_path):
    res = _run_compare(["--dir", str(tmp_path)], cwd=str(tmp_path))
    assert res.returncode == 0
    assert "nothing to compare" in res.stderr
