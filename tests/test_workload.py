"""Generator tests: ranges, shapes, distribution-shape sanity (pdf §5.1)."""

import numpy as np
import pytest

from skyline_tpu.ops import skyline_np
from skyline_tpu.workload import anti_correlated, correlated, generate, uniform


@pytest.mark.parametrize("method", ["uniform", "correlated", "anti_correlated"])
@pytest.mark.parametrize("dims", [2, 4, 8])
def test_ranges_and_dtype(rng, method, dims):
    x = generate(method, rng, 2000, dims, 0, 10000)
    assert x.shape == (2000, dims)
    assert x.dtype == np.float32
    assert (x >= 0).all() and (x <= 10000).all()
    np.testing.assert_array_equal(x, np.trunc(x))  # integer-valued


def test_generate_aliases_and_unknown(rng):
    generate("anti-correlated", rng, 10, 2, 0, 100)  # dash alias
    with pytest.raises(ValueError):
        generate("zipf", rng, 10, 2, 0, 100)


def test_distribution_shapes(rng):
    # Skyline-size ordering at 2D/200k/domain-10k per the reference's sanity
    # check (pdf §5.1: anti-corr 2961 >> correlated 1716 (all dupes) >> uniform 8).
    n = 50_000
    su = skyline_np(uniform(rng, n, 2, 0, 10000)).shape[0]
    sc_pts = skyline_np(correlated(rng, n, 2, 0, 10000))
    sa = skyline_np(anti_correlated(rng, n, 2, 0, 10000)).shape[0]
    assert su < 50
    assert sa > 500
    # correlated: the skyline collapses to duplicates of a near-origin point
    assert np.unique(sc_pts, axis=0).shape[0] < 25


def test_correlated_hugs_diagonal(rng):
    x = correlated(rng, 5000, 3, 0, 10000, rho=0.9)
    spread = x.max(axis=1) - x.min(axis=1)
    # noise band is ±(1-rho)*range = ±1000 -> within-point spread <= 2000
    assert (spread <= 2000).all()


def test_anti_correlated_hugs_antidiagonal(rng):
    x = anti_correlated(rng, 5000, 2, 0, 10000)
    sums = x.sum(axis=1)
    # target sum band: mean=10000, slack=0.0005*10000*2=10 (plus trunc/clip)
    inside = np.abs(sums - 10000) < 50
    assert inside.mean() > 0.95


def test_qos_workload(rng):
    from skyline_tpu.workload.generators import qos

    x = generate("qos", rng, 5000, 4, 0, 10000)
    assert x.shape == (5000, 4)
    assert (x >= 0).all() and (x <= 10000).all()
    # maximize-dims are flipped: good services (high thr/avail) have LOW
    # flipped values, so the skyline prefers them; sanity: skyline is small
    # vs anti-correlated but non-trivial
    s = skyline_np(x)
    assert 4 <= s.shape[0] <= 2500
    # dims truncation/extension
    assert generate("qos", rng, 100, 2, 0, 100).shape == (100, 2)
    assert generate("qos", rng, 100, 6, 0, 100).shape == (100, 6)


def test_producer_resume_offsets(capsys):
    """--start-id resumes the id sequence and keeps the every-threshold
    trigger cadence aligned to the GLOBAL sequence (the reference's producer
    always restarts at 0, unified_producer.py:160)."""
    from skyline_tpu.workload.producer import main

    main(["t", "uniform", "2", "0", "100", "q", "--sink", "stdout",
          "--count", "30", "--batch", "10", "--seed", "1",
          "--start-id", "95", "--query-threshold", "100",
          "--start-query-id", "3"])
    lines = [l for l in capsys.readouterr().out.splitlines() if l]
    data = [l.split("\t")[1] for l in lines if l.startswith("t\t")]
    trig = [l.split("\t")[1] for l in lines if l.startswith("q\t")]
    ids = [int(l.split(",")[0]) for l in data]
    assert ids == list(range(95, 125))
    # one trigger at the id-100 threshold crossing, none at 200
    assert trig == ["3,99"]


def test_simple_variant_distribution_signatures(rng):
    """P2's generators (kafka_producer.py:58-88) are DIFFERENT distributions
    from P1's: the simple anti-correlated pins every point's coordinate sum
    exactly to the center plane (no epsilon band), so at d=4 (where P1's
    band is eps=0.9, wide enough to dilute the anti-correlation) its sum
    spread collapses and its skyline signature differs."""
    from skyline_tpu.ops.dominance import skyline_np
    from skyline_tpu.workload.generators import (
        anti_correlated,
        simple_anti_correlated,
        simple_correlated,
    )

    n, d = 20000, 4
    p1 = anti_correlated(rng, n, d, 0, 10000)
    p2 = simple_anti_correlated(rng, n, d, 0, 10000)
    # sum spread: P2 sums sit on the plane (truncation/clipping error only),
    # P1's d=4 band is tens of thousands wide
    assert p2.sum(axis=1).std() * 10 < p1.sum(axis=1).std()
    # skyline-size signature differs: exact anti-correlation keeps far more
    # mutually non-dominated points than the diluted band
    s1 = skyline_np(p1[:5000]).shape[0]
    s2 = skyline_np(p2[:5000]).shape[0]
    assert s2 > 2 * s1

    # simple correlated: integer lattice, rows confined to base ± 10% domain
    c = simple_correlated(rng, n, d, 0, 10000)
    assert np.all(c == np.trunc(c))
    spread = c.max(axis=1) - c.min(axis=1)
    assert spread.max() <= 2 * 1000
    assert (0 <= c).all() and (c <= 10000).all()


def test_producer_variant_simple(capsys):
    """--variant simple routes the CLI distribution names onto P2's math."""
    from skyline_tpu.workload.producer import main

    main(["t", "anti-correlated", "4", "0", "10000", "q", "--sink", "stdout",
          "--count", "2000", "--batch", "500", "--seed", "7",
          "--query-threshold", "0", "--variant", "simple"])
    out = capsys.readouterr().out
    rows = np.array(
        [[float(v) for v in l.split("\t")[1].split(",")[1:]]
         for l in out.splitlines() if l.startswith("t\t")]
    )
    assert rows.shape == (2000, 4)
    # exact center-plane sums (20000) up to truncation/clip slack
    assert abs(np.median(rows.sum(axis=1)) - 20000) < 100
