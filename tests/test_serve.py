"""Query-serving plane: versioned snapshots, staleness bounds, deltas,
admission control, and the worker-integrated HTTP surface.

The acceptance test here is ``test_concurrent_readers_during_active_ingest``:
>=32 reader threads hammering GET /skyline while the worker ingests and
publishes, every response inside its staleness bound, every payload
digest-verified (zero torn reads), versions monotone per reader — then the
shed phase observes explicit 429s from a tight token bucket.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.ops import skyline_np
from skyline_tpu.serve import (
    AdmissionController,
    DeltaRing,
    QueryBridge,
    ServeConfig,
    SkylineServer,
    SnapshotStore,
    TokenBucket,
    snapshot_delta,
)
from skyline_tpu.serve.snapshot import points_digest
from skyline_tpu.stream import EngineConfig
from skyline_tpu.workload.generators import (
    anti_correlated,
    correlated,
    uniform,
)


def _get(url, timeout=10):
    """(status, json_doc, headers) — HTTPError surfaces as its status."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.load(r), dict(r.headers)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {}), dict(e.headers)


# --------------------------------------------------------------------------
# snapshot store
# --------------------------------------------------------------------------


def test_snapshot_versions_are_monotonic(rng):
    store = SnapshotStore(history=4)
    assert store.latest() is None and store.read() is None
    seen = []
    for _ in range(6):
        snap = store.publish(rng.uniform(0, 1, size=(8, 3)))
        seen.append(snap.version)
    assert seen == [1, 2, 3, 4, 5, 6]
    assert store.latest().version == store.head_version == 6
    # history is bounded: only the last 4 versions remain addressable
    assert store.get(6).version == 6 and store.get(3).version == 3
    assert store.get(1) is None and store.get(2) is None


def test_snapshot_is_frozen_and_never_aliases_the_engine_buffer(rng):
    store = SnapshotStore()
    src = rng.uniform(0, 1, size=(5, 2)).astype(np.float32)
    snap = store.publish(src)
    src[:] = -1.0  # engine reuses its buffer; the snapshot must not move
    assert float(snap.points.min()) >= 0.0
    assert snap.digest == points_digest(snap.points)
    with pytest.raises(ValueError):
        snap.points[0, 0] = 99.0


def test_staleness_bounds_age_and_version_lag(rng):
    store = SnapshotStore()
    store.publish(rng.uniform(0, 1, size=(4, 2)), now_ms=1000.0)
    # fresh on both axes
    rs = store.read(max_age_ms=500.0, max_version_lag=0, now_ms=1200.0)
    assert rs.fresh and rs.age_ms == 200.0 and rs.version_lag == 0
    # age bound violated
    rs = store.read(max_age_ms=500.0, now_ms=2000.0)
    assert not rs.fresh and rs.age_ms == 1000.0
    # lag bound: each ingest advance puts the snapshot one unit behind
    store.note_ingest(watermark_id=10)
    store.note_ingest(watermark_id=20)
    rs = store.read(max_version_lag=1, now_ms=1100.0)
    assert not rs.fresh and rs.version_lag == 2
    rs = store.read(max_version_lag=2, now_ms=1100.0)
    assert rs.fresh
    assert store.stream_watermark == 20
    # a publish resets the lag: the new snapshot covers the ingested data
    store.publish(rng.uniform(0, 1, size=(4, 2)), now_ms=1500.0)
    rs = store.read(max_version_lag=0, now_ms=1500.0)
    assert rs.fresh and rs.snapshot.watermark_id == 20
    # no bound specified -> always fresh
    assert store.read(now_ms=1e12).fresh


# --------------------------------------------------------------------------
# deltas
# --------------------------------------------------------------------------


def _brute_delta(old, new):
    o = {tuple(r) for r in np.asarray(old, np.float32).tolist()}
    n = {tuple(r) for r in np.asarray(new, np.float32).tolist()}
    return n - o, o - n


def _as_set(points):
    return {tuple(r) for r in np.asarray(points, np.float32).tolist()}


@pytest.mark.parametrize("gen", [uniform, correlated, anti_correlated])
def test_snapshot_delta_matches_bruteforce_set_diff(rng, gen):
    d = 3
    x = gen(rng, 800, d, 0, 10000)
    y = gen(rng, 800, d, 0, 10000)
    old = skyline_np(x)
    new = skyline_np(np.concatenate([x, y]))
    entered, left = snapshot_delta(old, new)
    want_entered, want_left = _brute_delta(old, new)
    assert _as_set(entered) == want_entered
    assert _as_set(left) == want_left
    # identity and empty edges
    e2, l2 = snapshot_delta(old, old)
    assert e2.shape[0] == 0 and l2.shape[0] == 0
    e3, l3 = snapshot_delta(np.empty((0, d), np.float32), new)
    assert _as_set(e3) == _as_set(new) and l3.shape[0] == 0


def test_delta_ring_merges_span_with_cancellation(rng):
    store = SnapshotStore()
    ring = DeltaRing(store, capacity=16)
    a = np.asarray([[1.0, 1.0], [2.0, 0.5]], np.float32)
    b = np.asarray([[1.0, 1.0], [0.2, 3.0]], np.float32)  # 2,0.5 left
    c = np.asarray([[1.0, 1.0], [2.0, 0.5]], np.float32)  # it came back
    store.publish(a)
    store.publish(b)
    store.publish(c)
    # v1 -> head: a == c, so the net delta must fully cancel
    entered, left, head = ring.since(1)
    assert head == 3 and entered.shape[0] == 0 and left.shape[0] == 0
    # v2 -> head: exactly the set difference between b and c
    entered, left, head = ring.since(2)
    we, wl = _brute_delta(b, c)
    assert _as_set(entered) == we and _as_set(left) == wl
    # current or future subscriber: empty catch-up
    e, l, h = ring.since(3)
    assert h == 3 and e.shape[0] == 0 and l.shape[0] == 0


def test_delta_ring_signals_gone_when_subscriber_falls_behind(rng):
    store = SnapshotStore()
    ring = DeltaRing(store, capacity=2)
    for _ in range(5):
        store.publish(rng.uniform(0, 1, size=(6, 2)))
    # ring holds transitions 3->4 and 4->5 only
    assert ring.oldest_since == 3
    assert ring.since(1) is None
    assert ring.since(2) is None
    got = ring.since(3)
    assert got is not None and got[2] == 5
    # the net merge still equals the direct v3 -> v5 set diff
    we, wl = _brute_delta(store.get(3).points, store.get(5).points)
    assert _as_set(got[0]) == we and _as_set(got[1]) == wl


# --------------------------------------------------------------------------
# admission control
# --------------------------------------------------------------------------


def test_token_bucket_sheds_past_burst_and_reports_retry_after():
    tb = TokenBucket(rate=10.0, burst=3)
    admitted = [tb.try_acquire()[0] for _ in range(5)]
    assert admitted[:3] == [True, True, True]
    assert admitted[3] is False
    ok, retry = tb.try_acquire()
    assert not ok and retry > 0
    # unlimited bucket never sheds
    assert all(TokenBucket(0.0, 1).try_acquire()[0] for _ in range(100))


def test_query_gate_bounds_concurrency_plus_queue():
    ctrl = AdmissionController(max_concurrent_queries=1, max_query_queue=1)
    gate = ctrl.queries
    assert gate.enter() and gate.enter()  # 1 active + 1 queued
    assert not gate.enter()  # shed
    assert ctrl.counters.get("queries_shed") == 1
    gate.leave()
    assert gate.enter()
    assert gate.depth == 2


# --------------------------------------------------------------------------
# HTTP surface (store-level, no engine)
# --------------------------------------------------------------------------


def test_http_skyline_deltas_and_errors(rng):
    store = SnapshotStore()
    ring = DeltaRing(store, capacity=2)
    srv = SkylineServer(store, deltas=ring, port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        code, doc, _ = _get(f"{base}/healthz")
        assert code == 200 and doc["ok"] and not doc["published"]
        # nothing published yet
        code, doc, _ = _get(f"{base}/skyline")
        assert code == 503
        pts = skyline_np(uniform(rng, 300, 2, 0, 10000))
        store.publish(pts, watermark_id=299)
        code, doc, _ = _get(f"{base}/skyline")
        assert code == 200 and doc["version"] == 1
        assert doc["skyline_size"] == pts.shape[0]
        got = np.asarray(doc["points"], np.float32)
        assert points_digest(got) == doc["digest"]
        # metadata-only read
        code, doc, _ = _get(f"{base}/skyline?points=0")
        assert code == 200 and "points" not in doc
        # csv wire format with version/digest headers
        with urllib.request.urlopen(f"{base}/skyline?format=csv") as r:
            body = r.read().decode()
            assert r.headers["X-Skyline-Version"] == "1"
            assert r.headers["X-Skyline-Size"] == str(pts.shape[0])
        assert len(body.splitlines()) == pts.shape[0]
        assert body.splitlines()[0] == format_tuple_line(0, pts[0])
        # bad params and unknown paths fail loudly, not silently
        code, _, _ = _get(f"{base}/skyline?max_age_ms=bogus")
        assert code == 400
        code, _, _ = _get(f"{base}/deltas")
        assert code == 400
        code, _, _ = _get(f"{base}/nope")
        assert code == 404
        # delta catch-up, then 410 Gone once the ring rolls past
        for _ in range(4):
            store.publish(skyline_np(uniform(rng, 300, 2, 0, 10000)))
        code, doc, _ = _get(f"{base}/deltas?since=4")
        assert code == 200 and doc["to_version"] == 5
        we, wl = _brute_delta(store.get(4).points, store.get(5).points)
        assert _as_set(np.asarray(doc["entered"], np.float32).reshape(-1, 2)) == we
        code, doc, _ = _get(f"{base}/deltas?since=1")
        assert code == 410 and doc["oldest_since"] == 3
    finally:
        srv.close()


def test_http_stale_read_rejected_unless_allowed(rng):
    store = SnapshotStore()
    srv = SkylineServer(store, bridge=QueryBridge(), port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        store.publish(rng.uniform(0, 1, size=(4, 2)))
        store.note_ingest(watermark_id=7)  # snapshot now lags by 1
        code, doc, _ = _get(f"{base}/skyline?max_version_lag=0")
        assert code == 503 and doc["version_lag"] == 1
        code, doc, _ = _get(
            f"{base}/skyline?max_version_lag=0&allow_stale=1&refresh=1"
        )
        assert code == 200 and doc["stale"] and doc["refresh_triggered"]
        # the refresh merge was queued for the worker loop to inject
        assert srv.bridge.depth == 1
        assert srv.admission.counters.get("stale_reads") == 2
        assert srv.admission.counters.get("stale_rejected") == 1
    finally:
        srv.close()


def test_http_read_shedding_emits_429_with_retry_after(rng):
    store = SnapshotStore()
    store.publish(rng.uniform(0, 1, size=(4, 2)))
    srv = SkylineServer(
        store,
        admission=AdmissionController(read_rate=1.0, read_burst=2),
        port=0,
    )
    try:
        base = f"http://127.0.0.1:{srv.port}"
        codes, headers = [], []
        for _ in range(6):
            code, _, hdr = _get(f"{base}/skyline?points=0")
            codes.append(code)
            headers.append(hdr)
        assert codes.count(200) == 2  # burst capacity
        assert codes.count(429) == 4  # everything past it sheds explicitly
        shed_hdr = headers[codes.index(429)]
        assert int(shed_hdr["Retry-After"]) >= 1
        st = srv.admission.stats()
        assert st["reads_shed"] == 4 and st["reads_served"] == 2
    finally:
        srv.close()


def test_http_query_gate_sheds_and_deadline_expires(rng):
    # a bridge nobody drains: the first query rides to its deadline (503),
    # a second concurrent one overflows the size-1/queue-0 gate (429)
    store = SnapshotStore()
    store.publish(rng.uniform(0, 1, size=(4, 2)))
    srv = SkylineServer(
        store,
        bridge=QueryBridge(),
        admission=AdmissionController(
            max_concurrent_queries=1,
            max_query_queue=0,
            query_deadline_ms=600.0,
        ),
        port=0,
    )
    try:
        base = f"http://127.0.0.1:{srv.port}"
        results = {}

        def post(tag):
            req = urllib.request.Request(
                f"{base}/query", data=b"{}", method="POST"
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    results[tag] = (r.status, json.load(r))
            except urllib.error.HTTPError as e:
                results[tag] = (e.code, json.loads(e.read() or b"{}"))

        t1 = threading.Thread(target=post, args=("first",))
        t1.start()
        time.sleep(0.2)  # first is in-flight, holding the gate
        post("second")
        t1.join(timeout=10)
        assert results["second"][0] == 429
        assert results["first"][0] == 503
        assert "deadline" in results["first"][1]["error"]
        st = srv.admission.stats()
        assert st["queries_shed"] == 1 and st["queries_timed_out"] == 1
    finally:
        srv.close()


# --------------------------------------------------------------------------
# worker integration
# --------------------------------------------------------------------------


def _worker_with_serve(dims=2, serve_config=None):
    bus = MemoryBus()
    worker = SkylineWorker(
        bus,
        EngineConfig(
            parallelism=2,
            algo="mr-angle",
            dims=dims,
            domain_max=10000.0,
            buffer_size=512,
        ),
        serve_port=0,
        serve_config=serve_config,
    )
    return bus, worker


def _ingest_window(bus, worker, x, id0, qid):
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(id0 + i, row) for i, row in enumerate(x)],
    )
    bus.produce("queries", format_trigger(qid, 0))
    while worker.step() > 0:
        pass


def test_forced_query_is_reference_parity_and_publishes(rng):
    bus, worker = _worker_with_serve(dims=3)
    try:
        port = worker.serve_server.port
        x = anti_correlated(rng, 1500, 3, 0, 10000)
        _ingest_window(bus, worker, x, 0, qid=0)
        v1 = worker.serve_server.store.head_version
        assert v1 >= 1
        # more data arrives but no bus trigger: only POST /query can see it
        y = anti_correlated(rng, 800, 3, 0, 10000)
        bus.produce_many(
            "input-tuples",
            [format_tuple_line(1500 + i, r) for i, r in enumerate(y)],
        )
        while worker.step() > 0:
            pass
        out = {}

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/query", data=b"{}", method="POST"
            )
            with urllib.request.urlopen(req, timeout=20) as r:
                out["doc"] = json.load(r)

        t = threading.Thread(target=post)
        t.start()
        deadline = time.time() + 15
        while t.is_alive() and time.time() < deadline:
            worker.step()  # the worker loop drains the query bridge
            time.sleep(0.005)
        t.join(timeout=1)
        expected = skyline_np(np.concatenate([x, y]))
        assert out["doc"]["skyline_size"] == expected.shape[0]
        # the forced merge also published a fresh snapshot for readers
        store = worker.serve_server.store
        assert store.head_version > v1
        assert store.latest().size == expected.shape[0]
        assert store.version_lag == 0
        # serve results never leak onto the output topic: the only emission
        # is the one bus-triggered window from the baseline ingest
        assert bus.size(worker.output_topic) == 1
        code, doc, _ = _get(f"http://127.0.0.1:{port}/skyline?max_version_lag=0")
        assert code == 200 and doc["skyline_size"] == expected.shape[0]
    finally:
        worker.close()


def test_concurrent_readers_during_active_ingest(rng):
    """Acceptance: >=32 concurrent snapshot readers during active ingest —
    every read inside its staleness bound, zero torn reads (digest-verified
    payloads), versions monotone per reader — then shedding engages."""
    bus, worker = _worker_with_serve(dims=2)
    try:
        port = worker.serve_server.port
        store = worker.serve_server.store
        # baseline snapshot so readers never race the first publish
        _ingest_window(bus, worker, uniform(rng, 400, 2, 0, 10000), 0, qid=0)
        assert store.head_version == 1

        stop = threading.Event()
        ingest_err = []

        def ingest():
            # the engine owner: keeps ingesting + publishing while readers
            # hammer the HTTP plane from other threads
            try:
                nxt = 400
                for qid in range(1, 40):
                    if stop.is_set():
                        return
                    x = uniform(rng, 400, 2, 0, 10000)
                    _ingest_window(bus, worker, x, nxt, qid=qid)
                    nxt += 400
            except Exception as e:  # pragma: no cover - diagnostic
                ingest_err.append(e)

        n_readers, reads_each = 32, 4
        errors = []
        url = (
            f"http://127.0.0.1:{port}/skyline"
            f"?max_age_ms=60000&max_version_lag=100000"
        )

        def reader(idx):
            versions = []
            try:
                for _ in range(reads_each):
                    code, doc, _ = _get(url, timeout=30)
                    if code != 200:
                        raise AssertionError(f"reader {idx}: HTTP {code} {doc}")
                    if doc["stale"]:
                        raise AssertionError(f"reader {idx}: stale served")
                    pts = np.asarray(doc["points"], np.float32).reshape(
                        -1, 2
                    )
                    if points_digest(pts) != doc["digest"]:
                        raise AssertionError(f"reader {idx}: torn read")
                    versions.append(doc["version"])
                if versions != sorted(versions):
                    raise AssertionError(
                        f"reader {idx}: versions regressed {versions}"
                    )
            except Exception as e:
                errors.append(e)

        it = threading.Thread(target=ingest)
        it.start()
        readers = [
            threading.Thread(target=reader, args=(i,))
            for i in range(n_readers)
        ]
        for t in readers:
            t.start()
        for t in readers:
            t.join(timeout=120)
        stop.set()
        it.join(timeout=120)
        assert not ingest_err, ingest_err
        assert not errors, errors[:3]
        assert store.head_version > 1  # ingest really ran under the readers
        served = worker.serve_server.admission.counters.get("reads_served")
        assert served == n_readers * reads_each

        # shed phase: same store behind a deliberately tight token bucket
        shed_srv = SkylineServer(
            store,
            admission=AdmissionController(read_rate=20.0, read_burst=4),
            port=0,
        )
        try:
            codes = []
            lock = threading.Lock()

            def hammer():
                for _ in range(8):
                    code, _, _ = _get(
                        f"http://127.0.0.1:{shed_srv.port}/skyline?points=0"
                    )
                    with lock:
                        codes.append(code)

            hs = [threading.Thread(target=hammer) for _ in range(8)]
            for t in hs:
                t.start()
            for t in hs:
                t.join(timeout=60)
            assert codes.count(429) > 0  # shedding engaged
            assert codes.count(200) >= 4  # but the burst was served
            assert shed_srv.admission.counters.get("reads_shed") == codes.count(
                429
            )
        finally:
            shed_srv.close()
    finally:
        worker.close()


def test_sliding_engine_publishes_versioned_snapshots(rng):
    from skyline_tpu.stream.sliding_engine import SlidingEngine

    cfg = EngineConfig(
        parallelism=2, algo="mr-angle", dims=2, domain_max=1000.0
    )
    eng = SlidingEngine(cfg, window_size=400, slide=200)
    store = SnapshotStore()
    eng.attach_snapshots(store)
    x = rng.uniform(0, 1000, size=(900, 2)).astype(np.float32)
    eng.process_records(np.arange(900, dtype=np.int64), x)
    assert store.version_lag == 1  # ingest noted, nothing published yet
    eng.process_trigger("0,0")
    eng.poll_results()
    snap = store.latest()
    assert snap is not None and snap.version == 1
    assert store.version_lag == 0 and snap.watermark_id == 899
    # sliding-specific provenance rides in the snapshot meta
    assert snap.meta["window_filled"] and snap.meta["slides_closed"] >= 2
    # the snapshot is the sliding window's skyline, not the full stream's
    lo = 900 - (900 - 400) % 200 - 400  # oldest row still inside the window
    assert _as_set(snap.points) == _as_set(skyline_np(x[lo:]))


def test_serve_cli_flags_reach_serve_config():
    from skyline_tpu.utils.config import parse_job_args

    cfg = parse_job_args(
        [
            "--serve", "0",
            "--serve-read-rate", "123.5",
            "--serve-read-burst", "9",
            "--serve-max-queries", "3",
            "--serve-query-queue", "5",
            "--serve-query-deadline-ms", "2500",
            "--serve-delta-ring", "33",
            "--serve-history", "17",
        ]
    )
    assert cfg.serve_port == 0
    sc = cfg.serve_config()
    assert isinstance(sc, ServeConfig)
    assert sc.read_rate == 123.5 and sc.read_burst == 9
    assert sc.max_concurrent_queries == 3 and sc.max_query_queue == 5
    assert sc.query_deadline_ms == 2500 and sc.delta_ring == 33
    assert sc.history == 17
    # off by default: no serving plane unless asked for
    assert parse_job_args([]).serve_port == -1


def test_worker_stats_include_serve_sections(rng):
    bus, worker = _worker_with_serve(dims=2)
    try:
        _ingest_window(bus, worker, uniform(rng, 300, 2, 0, 10000), 0, qid=0)
        code, doc, _ = _get(
            f"http://127.0.0.1:{worker.serve_server.port}/stats"
        )
        assert code == 200
        assert doc["snapshot_store"]["head_version"] == 1
        assert doc["delta_ring"]["head_version"] == 1
        assert doc["records_in"] == 300  # worker counters ride along
        assert doc["serve"]["bridge_depth"] == 0
    finally:
        worker.close()
