"""Device-resident ingest (stream/device_window.py + PartitionSet route):
routing, (pid, sum) sort, and SFS block slicing on the accelerator must be
result-identical to the host ingest path, including barrier semantics,
window-buffer reuse across windows, bookkeeping counters, and checkpointing.
On the CPU test platform "device" means the same backend, but the full code
path (upload, device routing, sorted-window slicing) is exercised."""

import numpy as np
import pytest

from skyline_tpu.ops.dominance import skyline_np
from skyline_tpu.stream import EngineConfig, SkylineEngine
from conftest import assert_same_set


def _anti(rng, n, d, domain=1000.0):
    base = rng.uniform(0, domain, (n, 1))
    return np.abs((domain - base) + rng.normal(0, 60, (n, d))).astype(
        np.float32
    )


@pytest.mark.parametrize("policy", ["lazy", "overlap"])
@pytest.mark.parametrize("algo", ["mr-dim", "mr-grid", "mr-angle"])
def test_device_ingest_matches_oracle(policy, algo, rng):
    n, d = 5000, 4
    x = _anti(rng, n, d)
    ids = np.arange(n)
    oracle_mid = skyline_np(x[:3000])
    oracle = skyline_np(x)
    cfg = EngineConfig(
        parallelism=4, algo=algo, dims=d, domain_max=1000.0,
        flush_policy=policy, ingest="device", overlap_rows=1024,
        emit_skyline_points=True,
    )
    eng = SkylineEngine(cfg)
    pos, results = 0, []
    for stop in (3000, n):
        while pos < stop:
            e = min(pos + 700, stop)
            eng.process_records(ids[pos:e], x[pos:e])
            pos = e
        eng.process_trigger(f"{len(results)},0")
        results.extend(eng.poll_results())
    assert results[0]["skyline_size"] == oracle_mid.shape[0]
    assert results[1]["skyline_size"] == oracle.shape[0]
    assert_same_set(results[1]["skyline_points"], oracle)


def test_device_matches_host_barrier_deferral(rng):
    """A trigger with a positive required id defers identically on both
    ingest paths, and the deferred answers match row-for-row."""
    n, d = 4000, 3
    x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    ids = np.arange(n)
    sizes = {}
    for ingest in ("host", "device"):
        cfg = EngineConfig(
            parallelism=2, algo="mr-angle", dims=d, domain_max=1000.0,
            flush_policy="lazy", ingest=ingest, emit_skyline_points=True,
        )
        eng = SkylineEngine(cfg)
        eng.process_records(ids[:2000], x[:2000])
        eng.process_trigger("0,3500")
        assert eng.poll_results() == []
        assert eng.inflight_queries == 1
        for pos in range(2000, n, 300):
            eng.process_records(ids[pos : pos + 300], x[pos : pos + 300])
        (res,) = eng.poll_results()
        sizes[ingest] = res["skyline_size"]
        pts = res["skyline_points"]
    assert sizes["host"] == sizes["device"]


def test_window_buffer_reuse_masks_stale_rows(rng):
    """A second, SMALLER window through the same engine must not resurrect
    rows of the first window left in the reused device buffer."""
    d = 3
    cfg = EngineConfig(
        parallelism=2, algo="mr-grid", dims=d, domain_max=1000.0,
        flush_policy="lazy", ingest="device", emit_skyline_points=True,
    )
    eng = SkylineEngine(cfg)
    x1 = rng.uniform(0, 1000, (3000, d)).astype(np.float32)
    eng.process_records(np.arange(3000), x1)
    eng.process_trigger("0,0")
    (r1,) = eng.poll_results()
    # second window: 400 new rows; the union state is sky(x1) + x2
    x2 = rng.uniform(0, 1000, (400, d)).astype(np.float32)
    eng.process_records(np.arange(3000, 3400), x2)
    eng.process_trigger("1,0")
    (r2,) = eng.poll_results()
    want = skyline_np(np.concatenate([x1, x2]))
    assert r2["skyline_size"] == want.shape[0]
    assert_same_set(r2["skyline_points"], want)


def test_chunk_split_and_growth(rng):
    """One giant process_records call splits into bucketed chunks and grows
    the accumulation buffer; results still match the oracle."""
    n, d = 150_000, 2
    x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    cfg = EngineConfig(
        parallelism=2, algo="mr-dim", dims=d, domain_max=1000.0,
        flush_policy="lazy", ingest="device",
    )
    eng = SkylineEngine(cfg)
    eng.process_records(np.arange(n), x)
    assert eng.pset.pending_rows_total == n
    eng.process_trigger("0,0")
    (res,) = eng.poll_results()
    assert res["skyline_size"] == skyline_np(x).shape[0]


def test_bookkeeping_counters_after_sync(rng):
    n, d = 2000, 3
    x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    ids = np.arange(100, 100 + n)
    for ingest in ("host", "device"):
        cfg = EngineConfig(
            parallelism=2, algo="mr-angle", dims=d, domain_max=1000.0,
            flush_policy="lazy", ingest=ingest,
        )
        eng = SkylineEngine(cfg)
        eng.process_records(ids[:900], x[:900])
        eng.process_records(ids[900:], x[900:])
        s = eng.stats()
        if ingest == "host":
            want = s
        else:
            assert s["partitions"]["records_seen"] == want["partitions"]["records_seen"]
            assert s["partitions"]["max_seen_id"] == want["partitions"]["max_seen_id"]
            assert s["records_in"] == want["records_in"]


def test_checkpoint_flushes_device_window(tmp_path, rng):
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    n, d = 3000, 3
    x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    cfg = EngineConfig(
        parallelism=2, algo="mr-grid", dims=d, domain_max=1000.0,
        flush_policy="lazy", ingest="device", emit_skyline_points=True,
    )
    eng = SkylineEngine(cfg)
    eng.process_records(np.arange(2000), x[:2000])
    path = str(tmp_path / "ck.npz")
    save_engine(eng, path)
    resumed = load_engine(path)
    resumed.process_records(np.arange(2000, n), x[2000:])
    resumed.process_trigger("0,0")
    (res,) = resumed.poll_results()
    want = skyline_np(x)
    assert res["skyline_size"] == want.shape[0]
    assert_same_set(res["skyline_points"], want)


def test_large_ids_rejected():
    cfg = EngineConfig(
        parallelism=2, algo="mr-dim", dims=2, domain_max=1000.0,
        flush_policy="lazy", ingest="device",
    )
    eng = SkylineEngine(cfg)
    with pytest.raises(ValueError, match="int32"):
        eng.process_records(
            np.array([2**31], dtype=np.int64),
            np.zeros((1, 2), dtype=np.float32),
        )


def test_device_ingest_requires_lazy_single_device():
    with pytest.raises(ValueError):
        SkylineEngine(
            EngineConfig(flush_policy="incremental", ingest="device")
        )


@pytest.mark.parametrize("algo", ["mr-dim", "mr-angle"])
def test_rank_flush_matches_oracle_multiwindow(algo, rng, monkeypatch):
    """The rank-cascade SFS flush (device path + interpret-mode Pallas)
    must match the oracle across TWO flushes — the second exercises the
    shared rank universe (window + live sky prefixes) and the rank-space
    cleanup."""
    monkeypatch.setenv("SKYLINE_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("SKYLINE_RANK_CASCADE", "1")
    from skyline_tpu.stream import device_window as dw

    assert dw.rank_flush_enabled()
    n, d = 4000, 4
    x = _anti(rng, n, d)
    # duplicates across the flush boundary: tie semantics under ranks
    x[2100:2110] = x[100:110]
    cfg = EngineConfig(
        parallelism=2, algo=algo, dims=d, domain_max=1000.0,
        flush_policy="lazy", ingest="device", emit_skyline_points=True,
    )
    eng = SkylineEngine(cfg)
    eng.process_records(np.arange(2000), x[:2000])
    eng.process_trigger("0,0")
    (r1,) = eng.poll_results()
    want1 = skyline_np(x[:2000])
    assert r1["skyline_size"] == want1.shape[0]
    eng.process_records(np.arange(2000, n), x[2000:])
    eng.process_trigger("1,0")
    (r2,) = eng.poll_results()
    want2 = skyline_np(x)
    assert r2["skyline_size"] == want2.shape[0]
    assert_same_set(r2["skyline_points"], want2)


def test_rank_flush_off_by_env(rng, monkeypatch):
    monkeypatch.setenv("SKYLINE_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("SKYLINE_RANK_CASCADE", "0")
    from skyline_tpu.stream import device_window as dw

    assert not dw.rank_flush_enabled()


def test_window_capacity_presizes_accumulation_buffer(rng):
    cfg = EngineConfig(
        parallelism=2, algo="mr-dim", dims=2, domain_max=1000.0,
        flush_policy="lazy", ingest="device", window_capacity=200_000,
    )
    eng = SkylineEngine(cfg)
    x = rng.uniform(0, 1000, (1000, 2)).astype(np.float32)
    eng.process_records(np.arange(1000), x)
    cap0 = eng.pset._dev_cap
    assert cap0 >= 200_000  # pre-sized, not the 131072 floor
    # a full expected window never reallocates
    for i in range(1, 5):
        eng.process_records(
            np.arange(i * 1000, (i + 1) * 1000),
            rng.uniform(0, 1000, (1000, 2)).astype(np.float32),
        )
    assert eng.pset._dev_cap == cap0
