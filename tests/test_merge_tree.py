"""Pruned tournament-tree global merge (ISSUE 4): byte identity with the
flat union pass across workload shapes, pruning edge cases, delta merges
routed through the tree, and the overlapped query sync emitting the same
results as the blocking path under interleaved flushes."""

import numpy as np
import pytest

from skyline_tpu.stream.batched import PartitionSet
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine
# shared state/digest helpers live in conftest.py (the audit plane's
# tests reuse the same builders — satellite of ISSUE 10)
from conftest import assert_same_merge, fill_pset, gen_points, merge_state


@pytest.mark.parametrize("kind", ["uniform", "correlated", "anti"])
@pytest.mark.parametrize("d", [2, 4, 8])
@pytest.mark.parametrize("P", [1, 3, 8])
@pytest.mark.parametrize("prune", ["1", "0"])
def test_tree_matches_flat(monkeypatch, kind, d, P, prune):
    """Property grid: the tree (with and without the witness prefilter) is
    byte-identical to the flat union pass. d=2 exercises the unchanged
    sweep path, so the grid also pins that the knobs are inert there."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    monkeypatch.setenv("SKYLINE_MERGE_PRUNE", prune)
    results = {}
    for tree in ("1", "0"):
        monkeypatch.setenv("SKYLINE_MERGE_TREE", tree)
        rng = np.random.default_rng(17)
        pset = PartitionSet(P, d)
        fill_pset(pset, rng, gen_points(rng, int(1200), d, kind), P)
        results[tree] = merge_state(pset)
    assert_same_merge(
        results["1"], results["0"], f"(kind={kind} d={d} P={P} prune={prune})"
    )


def test_all_partitions_pruned_but_one(monkeypatch):
    """A near-origin partition whose witness dominates every other
    partition's min-corner prunes all of them: the tree degenerates to one
    leaf and still matches the flat recompute exactly."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    P, d = 8, 4

    def build(tree, prune):
        monkeypatch.setenv("SKYLINE_MERGE_TREE", tree)
        monkeypatch.setenv("SKYLINE_MERGE_PRUNE", prune)
        rng = np.random.default_rng(3)
        pset = PartitionSet(P, d)
        strong = (rng.random((64, d)) * 0.01).astype(np.float32)
        pset.add_batch(0, strong, max_id=64, now_ms=0.0)
        for p in range(1, P):
            weak = (0.5 + rng.random((400, d)) * 0.5).astype(np.float32)
            pset.add_batch(p, weak, max_id=4000, now_ms=0.0)
        pset.flush_all()
        return pset, merge_state(pset)

    pruned_set, pruned = build("1", "1")
    noprune_set, noprune = build("1", "0")
    _, flat = build("0", "1")
    assert_same_merge(pruned, flat, "(pruned tree vs flat)")
    assert_same_merge(noprune, flat, "(unpruned tree vs flat)")
    assert pruned_set.last_tree_info["partitions_pruned"] == P - 1
    assert pruned_set.last_tree_info["levels"] == 0  # single surviving leaf
    assert noprune_set.last_tree_info["partitions_pruned"] == 0
    assert noprune_set.last_tree_info["levels"] == 3  # 8 -> 4 -> 2 -> 1
    # all weak partitions contribute zero survivors either way
    assert (np.asarray(pruned[1])[1:] == 0).all()


def test_single_nonempty_partition(monkeypatch):
    """One live partition: the tree is a lone leaf (levels 0) and its
    result matches the flat pass byte for byte."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    P, d = 8, 3
    results = {}
    for tree in ("1", "0"):
        monkeypatch.setenv("SKYLINE_MERGE_TREE", tree)
        rng = np.random.default_rng(9)
        pset = PartitionSet(P, d)
        pset.add_batch(
            2, rng.random((700, d)).astype(np.float32), max_id=700, now_ms=0.0
        )
        pset.flush_all()
        results[tree] = (merge_state(pset), pset.last_tree_info)
    assert_same_merge(results["1"][0], results["0"][0], "(single partition)")
    assert results["1"][1]["levels"] == 0
    assert results["0"][1] is None  # flat path never ran the tree


def test_delta_merges_route_through_tree(monkeypatch):
    """With the epoch cache live, dirty-subset merges feed dirty skylines
    and cached clean segments as tree leaves — results stay byte-identical
    to the flat delta across interleaved flush/trigger rounds."""
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "1")
    P, d = 8, 3

    def run(tree):
        monkeypatch.setenv("SKYLINE_MERGE_TREE", tree)
        rng = np.random.default_rng(7)
        pset = PartitionSet(P, d)
        out = []
        for rnd in range(6):
            x = rng.random((900, d)).astype(np.float32)
            pids = rng.integers(0, P, len(x))
            live = range(P) if rnd < 2 else range(rnd % P, (rnd % P) + 2)
            for p in live:
                rows = np.ascontiguousarray(x[pids == p])
                if rows.shape[0]:
                    pset.add_batch(p, rows, max_id=len(x), now_ms=0.0)
            pset.flush_all()
            out.append(merge_state(pset))
            # repeat trigger over unchanged state: exact cache hit
            out.append(merge_state(pset))
        return out, pset

    a, pa = run("1")
    b, pb = run("0")
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert_same_merge(ra, rb, f"(round {i})")
    # both sides took the same hit/miss/delta decisions
    assert pa.merge_cache_hits == pb.merge_cache_hits > 0
    assert pa.merge_delta_merges == pb.merge_delta_merges > 0
    # the tree side actually ran tree merges; zero launches on exact hits
    assert pa.merge_tree_merges > 0
    assert pb.merge_tree_merges == 0


@pytest.mark.parametrize("flush_policy", ["incremental", "lazy"])
def test_overlapped_sync_matches_blocking(monkeypatch, flush_policy):
    """The overlapped query sync (merge launched at trigger, harvested at
    the next drain) emits the same results as the blocking path while
    flushes land between launch and harvest."""

    def run(overlap):
        monkeypatch.setenv("SKYLINE_QUERY_OVERLAP", overlap)
        rng = np.random.default_rng(11)
        eng = SkylineEngine(
            EngineConfig(
                parallelism=2,
                dims=3,
                emit_skyline_points=True,
                flush_policy=flush_policy,
            )
        )
        out = []
        nid = 0
        overlapped = 0
        for rnd in range(4):
            x = rng.random((1500, 3)).astype(np.float32)
            ids = np.arange(nid, nid + len(x))
            nid += len(x)
            eng.process_records(ids, x, now_ms=float(rnd))
            # required=0: the barrier passes on every partition, so the
            # trigger takes the device-merge path (launch-at-trigger)
            eng.process_trigger(f"q{rnd},0", now_ms=rnd + 0.5)
            overlapped += eng._inflight_merge is not None
            # more ingest lands (and flushes) while the merge is in flight
            y = rng.random((800, 3)).astype(np.float32)
            ids = np.arange(nid, nid + len(y))
            nid += len(y)
            eng.process_records(ids, y, now_ms=rnd + 0.7)
            out.extend(eng.poll_results())
        if overlap == "1":
            assert overlapped == 4  # every trigger actually launched async
        else:
            assert overlapped == 0
        return out

    a = run("1")
    b = run("0")
    assert len(a) == len(b) == 4
    for ra, rb in zip(a, b):
        assert ra["query_id"] == rb["query_id"]
        assert ra["skyline_size"] == rb["skyline_size"]
        assert sorted(map(tuple, ra["skyline_points"])) == sorted(
            map(tuple, rb["skyline_points"])
        )


def test_overlap_consecutive_triggers(monkeypatch):
    """A second trigger harvests the first's in-flight merge before
    launching its own: results emit in trigger order, one per query."""
    monkeypatch.setenv("SKYLINE_QUERY_OVERLAP", "1")
    rng = np.random.default_rng(5)
    eng = SkylineEngine(
        EngineConfig(parallelism=2, dims=3, emit_skyline_points=True)
    )
    x = rng.random((3000, 3)).astype(np.float32)
    eng.process_records(np.arange(3000), x, now_ms=0.0)
    eng.process_trigger("qa,0", now_ms=1.0)
    assert eng._inflight_merge is not None  # qa launched, not yet emitted
    eng.process_trigger("qb,0", now_ms=2.0)
    res = eng.poll_results()
    assert [r["query_id"] for r in res] == ["qa", "qb"]
    assert res[0]["skyline_size"] == res[1]["skyline_size"]
    # the repeat trigger over unchanged state was a pure cache hit
    assert eng.pset.merge_cache_hits >= 1
