"""Per-query EXPLAIN plane (ISSUE 9): causal execution-plan records.

Pins the tentpole's contract end to end: the recorder ring and its
lookup semantics, the pure delta/diff helpers, the PartitionSet hooks on
every merge path (cache hit / tree / tree_delta / delta / flat) with the
forced-prune witness reasons, the engine e2e that drives one query down
each path and checks the plan against the result, the attribution
property (plan blocks reconcile with the telemetry counters across
policy x distribution x d), byte-identity of answers with the plane on
vs off, both HTTP surfaces, and the ``python -m skyline_tpu.explain``
CLI.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from skyline_tpu.metrics.httpstats import StatsServer
from skyline_tpu.stream import EngineConfig, SkylineEngine
from skyline_tpu.stream.batched import PartitionSet
from skyline_tpu.stream.window import prune_witness_mask
from skyline_tpu.telemetry import Telemetry
from skyline_tpu.telemetry.explain import (
    QueryPlan,
    cascade_delta,
    format_diff,
    format_plan,
    kernel_delta,
    plan_diff,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


# ------------------------------------------------------------ recorder ring


def test_recorder_ring_bounds_and_lookup():
    from skyline_tpu.telemetry.explain import ExplainRecorder

    rec = ExplainRecorder(capacity=4)
    assert rec.latest() is None and rec.by_version(1) is None
    for i in range(6):
        rec.add({
            "trace_id": f"t-{i}",
            "publish": {"version": min(i, 4)},  # 4 and 5 share version 4
        })
    assert len(rec) == 4
    doc = rec.doc()
    assert doc == {
        "depth": 4, "recorded_total": 6, "ring_capacity": 4, "partial": True,
    }
    # evicted plans are gone; retained ones resolve by version and trace
    assert rec.by_version(0) is None and rec.by_version(1) is None
    assert rec.by_version(2)["trace_id"] == "t-2"
    # deduped publishes map several plans to one version: newest wins
    assert rec.by_version(4)["trace_id"] == "t-5"
    assert rec.by_trace("t-3")["trace_id"] == "t-3"
    assert rec.by_trace("t-0") is None
    assert rec.latest()["trace_id"] == "t-5"
    # add() stamps the monotonic seq + wall time
    assert rec.latest()["seq"] == 6 and rec.latest()["t_ms"] > 0


def test_kernel_and_cascade_delta():
    k1 = ("merge_step", 4, 4096, "cpu", False)
    k2 = ("sweep", 2, 1024, "cpu", True)
    before = {k1: (2, 10.0)}
    after = {k1: (5, 16.5), k2: (1, 30.0)}
    rows = kernel_delta(before, after)
    # sorted by attributed wall time, not total
    assert [r["variant"] for r in rows] == ["sweep", "merge_step"]
    assert rows[1] == {
        "variant": "merge_step", "d": 4, "n_bucket": 4096, "backend": "cpu",
        "mp": False, "calls": 3, "wall_ms": 6.5,
    }
    assert rows[0]["calls"] == 1 and rows[0]["mp"] is True
    # signatures with no new calls are excluded from the window
    assert kernel_delta(after, after) == []

    c = cascade_delta(
        {"prefilter_seen": 10, "prefilter_dropped": 4, "bf16_resolved": 1},
        {"prefilter_seen": 25, "prefilter_dropped": 9, "bf16_resolved": 1,
         "prefilter_enabled": True, "mixed_precision": False},
    )
    assert c == {
        "prefilter_seen": 15, "prefilter_dropped": 5, "bf16_resolved": 0,
        "prefilter_enabled": True, "mixed_precision": False,
    }
    # first window diffs against the empty mark: totals pass through
    assert cascade_delta({}, {"prefilter_seen": 3})["prefilter_seen"] == 3


def test_plan_diff_excludes_volatile_fields():
    a = QueryPlan("t-a", "q1")
    a.merge = {"path": "tree", "cached": False, "dirty": [0, 1]}
    a.timing = {"local_ms": 5.0, "global_ms": 9.0}
    a.kernels = [{"variant": "merge_step", "calls": 1, "wall_ms": 3.0}]
    da = a.to_doc()
    da["seq"], da["t_ms"] = 1, 100.0
    b = QueryPlan("t-b", "q2")
    b.merge = {"path": "tree_delta", "cached": False, "dirty": [1]}
    b.timing = {"local_ms": 50.0, "global_ms": 90.0}
    b.kernels = [{"variant": "merge_step", "calls": 1, "wall_ms": 30.0}]
    db = b.to_doc()
    db["seq"], db["t_ms"] = 2, 200.0
    rows = plan_diff(da, db)
    keys = [k for k, _, _ in rows]
    # decision fields only: ids, seq/t_ms, and every *_ms excluded
    assert "merge.path" in keys
    assert ("merge.dirty", [0, 1], [1]) in rows
    assert not any("wall_ms" in k or k.endswith("_ms") for k in keys)
    assert not any(k.startswith(("trace_id", "seq", "t_ms")) for k in keys)
    assert ("merge.path", "tree", "tree_delta") in rows
    # identical decisions -> explicitly reported as such
    assert "decision-identical" in format_diff(da, da)
    assert "tree_delta" in format_diff(da, db)
    # rendering never throws on partial plans (merge-only, no publish)
    assert "merge path=tree" in format_plan(da)


def test_prune_witness_mask_reasons():
    # summaries rows: [min_corner(d) | witness(d) | min_sum | max_sum]
    d = 2
    summaries = np.array([
        [1, 1, 1, 1, 2, 2],        # p0: witness (1,1) dominates p1+p3
        [5, 5, 6, 6, 12, 12],      # p1: pruned by p0
        [0, 9, 0, 9, 9, 9],        # p2: survives ((1,1) !<= (0,9))
        [7, 7, 8, 8, 16, 16],      # p3: pruned by p0 (p2 checked first
                                   #     but (0,9) does not dominate)
        [np.inf] * 6,              # p4: empty, prunes nothing
    ], dtype=np.float64)
    alive = np.array([True, True, True, True, False])
    pruned, witness_of = prune_witness_mask(summaries, alive, d)
    assert pruned.tolist() == [False, True, False, True, False]
    assert witness_of.tolist() == [-1, 0, -1, 0, -1]
    # dead partitions neither prune nor get pruned: with p0 out, p3 now
    # falls to p1's witness ((6,6) < min-corner (7,7)), p1 survives
    alive2 = np.array([False, True, True, True, False])
    pruned2, wo2 = prune_witness_mask(summaries, alive2, d)
    assert pruned2.tolist() == [False, False, False, True, False]
    assert wo2[3] == 1 and not pruned2[0]


# ------------------------------------------- PartitionSet hooks, all paths


def test_partitionset_plan_every_merge_path(rng, monkeypatch):
    monkeypatch.delenv("SKYLINE_MERGE_TREE", raising=False)
    monkeypatch.delenv("SKYLINE_MERGE_CACHE", raising=False)
    P, d = 4, 3
    ps = PartitionSet(P, d, buffer_size=128)
    # partition 0 holds a universal dominator; 1..3 live far above it, so
    # the tournament tree MUST prune them all with witness reason p0
    ps.add_batch(0, np.array([[1.0, 1.0, 1.0]], np.float32), max_id=0,
                 now_ms=0.0)
    for p in range(1, P):
        ps.add_batch(p, rng.uniform(500, 999, (20, d)).astype(np.float32),
                     max_id=0, now_ms=0.0)
    ps.flush_all()

    plan = QueryPlan("t-1", "q0")
    ps.set_explain(plan)
    _, _, g, _ = ps.global_merge_stats(emit_points=True)
    assert ps._explain is None, "launch must claim the plan one-shot"
    assert plan.merge["path"] == "tree" and plan.merge["cached"] is False
    assert plan.merge["dirty"] == [0, 1, 2, 3] and plan.merge["clean"] == []
    assert len(plan.merge["epoch_key"]) > 0
    assert plan.merge["skyline_size"] == int(g) == 1
    wit = {e["partition"]: e["witness"] for e in plan.tree["pruned"]}
    assert wit == {1: 0, 2: 0, 3: 0}
    assert plan.tree["partitions_pruned"] == 3

    # repeat trigger: epoch cache answers, no kernels
    plan2 = QueryPlan("t-2", "q1")
    ps.set_explain(plan2)
    ps.global_merge_stats(emit_points=True)
    assert plan2.merge["path"] == "cache_hit" and plan2.merge["cached"]
    # on a pure hit every populated partition serves from cache unchanged
    assert plan2.merge["dirty"] == []
    assert plan2.merge["clean"] == [0, 1, 2, 3]
    assert plan2.merge["dirty_fraction"] == 0.0
    assert plan2.merge["epoch_key"] == plan.merge["epoch_key"]

    # dirty one partition of four -> tree_delta with the dirty set named
    ps.add_batch(2, rng.uniform(500, 999, (8, d)).astype(np.float32),
                 max_id=1, now_ms=0.0)
    ps.flush_all()
    plan3 = QueryPlan("t-3", "q2")
    ps.set_explain(plan3)
    ps.global_merge_stats(emit_points=True)
    assert plan3.merge["path"] == "tree_delta"
    assert plan3.merge["dirty"] == [2]
    assert sorted(plan3.merge["clean"]) == [0, 1, 3]
    assert plan3.merge["dirty_fraction"] == pytest.approx(0.25)
    assert plan3.merge["delta_rows"] >= 1
    assert plan3.merge["epoch_key"] != plan.merge["epoch_key"]

    # tree off: the same dirty-subset decision reads "delta"
    monkeypatch.setenv("SKYLINE_MERGE_TREE", "0")
    ps.add_batch(1, rng.uniform(500, 999, (8, d)).astype(np.float32),
                 max_id=2, now_ms=0.0)
    ps.flush_all()
    plan4 = QueryPlan("t-4", "q3")
    ps.set_explain(plan4)
    ps.global_merge_stats(emit_points=True)
    assert plan4.merge["path"] == "delta" and plan4.tree is None

    # cache plane off entirely: full flat recompute, everything dirty
    monkeypatch.setenv("SKYLINE_MERGE_CACHE", "0")
    plan5 = QueryPlan("t-5", "q4")
    ps.set_explain(plan5)
    ps.global_merge_stats(emit_points=True)
    assert plan5.merge["path"] == "flat"
    assert plan5.merge["dirty"] == [0, 1, 2, 3]
    assert plan5.merge["dirty_fraction"] is None  # stale-value guard

    # set_explain(None) clears a parked plan (engine trigger-abort path)
    ps.set_explain(QueryPlan("t-6", "q5"))
    ps.set_explain(None)
    assert ps._explain is None


# --------------------------------------------------------------- engine e2e


def _ingest(eng, ids, x):
    eng.process_records(np.asarray(ids, dtype=np.int64), x)


def test_engine_e2e_plan_per_merge_path(monkeypatch):
    """Acceptance: force one query through each merge path and check the
    plan's path, pruned set, cascade drops, dispatch signatures, and
    publish watermark against the engine's own result/counters."""
    monkeypatch.delenv("SKYLINE_EXPLAIN", raising=False)
    from skyline_tpu.serve import SnapshotStore

    tel = Telemetry()
    eng = SkylineEngine(
        EngineConfig(parallelism=4, dims=4, domain_max=1000.0,
                     algo="mr-dim", emit_skyline_points=True),
        telemetry=tel,
    )
    eng.attach_snapshots(SnapshotStore())
    rng = np.random.default_rng(7)
    x = rng.uniform(1, 999, size=(3000, 4)).astype(np.float32)
    _ingest(eng, np.arange(x.shape[0]), x)

    P = eng.config.num_partitions
    eng.process_trigger("q1,0")
    (r1,) = eng.poll_results()
    p1 = tel.explain.latest()
    assert p1["trace_id"] == r1["trace_id"]
    assert p1["merge"]["path"] == "tree" and not p1["merge"]["cached"]
    assert p1["merge"]["dirty"] == list(range(P))
    assert p1["merge"]["skyline_size"] == r1["skyline_size"]
    assert p1["tree"]["levels"] >= 1 and p1["tree"]["considered"] >= 1
    # cascade attribution covers this query's ingest window: every row of
    # the stream went through the d>2 grid prefilter
    assert p1["cascade"]["prefilter_enabled"] is True
    assert p1["cascade"]["prefilter_seen"] > 0
    assert p1["cascade"]["prefilter_dropped"] >= 0
    # dispatch signatures with attributed wall time
    assert p1["kernels"], "window must attribute at least one kernel"
    for k in p1["kernels"]:
        assert set(k) == {"variant", "d", "n_bucket", "backend", "mp",
                          "calls", "wall_ms"}
        assert k["calls"] >= 1 and k["wall_ms"] >= 0
    assert any(k["d"] == 4 for k in p1["kernels"])
    assert p1["publish"]["version"] == 1
    assert p1["publish"]["deduped"] is False
    assert "event_wm_ms" in p1["publish"]
    assert p1["timing"]["latency_ms"] >= p1["timing"]["global_ms"]

    # repeat trigger, no new data: cache hit, publish dedupes to v1
    eng.process_trigger("q2,0")
    (r2,) = eng.poll_results()
    p2 = tel.explain.latest()
    assert p2["merge"]["path"] == "cache_hit"
    assert p2["merge"]["dirty"] == []
    assert p2["publish"] == {"version": 1, "deduped": True,
                             "event_wm_ms": p1["publish"]["event_wm_ms"]}
    # the cache-hit window launched no merge kernels
    assert not any("merge" in k["variant"] for k in p2["kernels"])

    # mr-dim range-partitions on dim 0: rows with v0 below the first
    # range boundary all land on partition 0 -> small dirty fraction ->
    # delta path
    small = rng.uniform(1, 999, size=(64, 4)).astype(np.float32)
    small[:, 0] = rng.uniform(1, 0.8 * 1000.0 / P, size=64)
    _ingest(eng, np.arange(3000, 3064), small)
    eng.process_trigger("q3,0")
    (r3,) = eng.poll_results()
    p3 = tel.explain.latest()
    assert p3["merge"]["path"] == "tree_delta"
    assert p3["merge"]["dirty"] == [0]
    assert sorted(p3["merge"]["clean"]) == list(range(1, P))
    assert p3["merge"]["delta_rows"] >= 1
    assert p3["merge"]["skyline_size"] == r3["skyline_size"]
    assert p3["publish"]["version"] >= 1
    assert p3["cascade"]["prefilter_seen"] == 64  # just this window

    # plan plumbing: ring, counter, /stats block, explain child spans
    assert tel.counters.get("explain.records") == 3
    assert eng.stats()["explain"]["recorded_total"] == 3
    names = [s["name"] for s in tel.spans.snapshot()]
    assert "explain/tree" in names and "explain/cache_hit" in names
    assert "explain/tree_delta" in names
    for s in tel.spans.snapshot():
        if s["name"] == "explain/tree":
            assert s["trace_id"] == r1["trace_id"]
    # flight-ring rows of the traced queries carry their trace_id
    flight = [e for e in tel.flight.snapshot() if "trace_id" in e]
    assert flight and {e["trace_id"] for e in flight} <= {
        r1["trace_id"], r2["trace_id"], r3["trace_id"],
    }


def test_engine_host_path_plan(monkeypatch):
    """Per-partition host merges (pending completeness barriers) never
    touch the device hooks; the finalizer stamps the fallback 'host' path
    so every answer still gets a plan."""
    monkeypatch.delenv("SKYLINE_EXPLAIN", raising=False)
    tel = Telemetry()
    eng = SkylineEngine(
        EngineConfig(parallelism=4, dims=3, domain_max=1000.0),
        telemetry=tel,
    )
    rng = np.random.default_rng(3)
    _ingest(eng, np.arange(800),
            rng.uniform(1, 999, size=(800, 3)).astype(np.float32))
    # require id 801: every partition's completeness barrier is pending,
    # so each answers host-side as its next ingest arrives
    eng.process_trigger("q1,801")
    assert eng.poll_results() == []
    _ingest(eng, np.arange(800, 1200),
            rng.uniform(1, 999, size=(400, 3)).astype(np.float32))
    (r,) = eng.poll_results()
    plan = tel.explain.latest()
    assert plan["merge"] == {"path": "host", "cached": False,
                             "skyline_size": r["skyline_size"]}
    assert plan["tree"] is None
    assert plan["timing"]["total_ms"] >= 0


def test_engine_explain_off_records_nothing(monkeypatch):
    monkeypatch.setenv("SKYLINE_EXPLAIN", "0")
    tel = Telemetry()
    eng = SkylineEngine(
        EngineConfig(parallelism=2, dims=3, domain_max=1000.0),
        telemetry=tel,
    )
    rng = np.random.default_rng(3)
    _ingest(eng, np.arange(500),
            rng.uniform(1, 999, size=(500, 3)).astype(np.float32))
    eng.process_trigger("q1,0")
    (r,) = eng.poll_results()
    assert r["skyline_size"] > 0
    assert len(tel.explain) == 0
    assert tel.counters.get("explain.records") == 0
    assert "explain" not in eng.stats()


# ------------------------------------------------- attribution property


GRID = [
    ("incremental", "uniform", 3),
    ("incremental", "anti", 2),   # d=2: sweep path, prefilter/tree off
    ("lazy", "uniform", 4),
    ("lazy", "anti", 4),
]


def _make_stream(dist, d, n, seed):
    rng = np.random.default_rng(seed)
    if dist == "uniform":
        x = rng.uniform(1, 999, (n, d))
    else:
        base = rng.uniform(1, 999, (n, 1))
        x = np.clip(np.abs((999 - base) + rng.normal(0, 60, (n, d))), 1, 999)
    return rng, x.astype(np.float32)


def _drive(policy, dist, d, *, explain):
    from skyline_tpu.serve import SnapshotStore

    tel = Telemetry()
    eng = SkylineEngine(
        EngineConfig(parallelism=4, dims=d, domain_max=1000.0,
                     buffer_size=256, flush_policy=policy,
                     emit_skyline_points=True),
        telemetry=tel,
    )
    eng.attach_snapshots(SnapshotStore())
    assert eng._explain_on is explain
    rng, x = _make_stream(dist, d, 1200, seed=11)
    results = []
    pos = 0
    for i, stop in enumerate((400, 900, 1200)):
        while pos < stop:
            end = min(pos + int(rng.integers(50, 300)), stop)
            _ingest(eng, np.arange(pos, end), x[pos:end])
            pos = end
        eng.process_trigger(f"q{i},0")
        results.extend(eng.poll_results())
    eng.process_trigger("q3,0")  # repeat: cache-hit leg
    results.extend(eng.poll_results())
    return tel, eng, results


@pytest.mark.parametrize("policy,dist,d", GRID)
def test_property_plans_reconcile_with_counters(policy, dist, d,
                                                monkeypatch):
    """Plan attribution must agree with the aggregate telemetry the plane
    claims to explain: per-path counts, pruned-partition totals, and
    flush-cascade totals all reconcile; answers are byte-identical with
    the plane off."""
    monkeypatch.delenv("SKYLINE_EXPLAIN", raising=False)
    tel, eng, results = _drive(policy, dist, d, explain=True)
    plans = tel.explain.snapshot()
    assert len(plans) == len(results) == 4
    assert [p["trace_id"] for p in plans] == [
        r["trace_id"] for r in results
    ]
    counters = tel.counters.snapshot()

    # merge-path attribution == cache-plane counters
    hits = [p for p in plans if p["merge"]["path"] == "cache_hit"]
    assert len(hits) == counters.get("merge.cache_hit", 0) >= 1
    for p in hits:
        assert p["publish"]["deduped"] is True
    # every plan's skyline size matches its emitted result
    for p, r in zip(plans, results):
        assert p["merge"]["skyline_size"] == r["skyline_size"]
        assert p["publish"]["version"] <= len(results)

    # pruned-partition totals == the merge.partitions_pruned counter
    pruned_total = sum(
        (p["tree"] or {}).get("partitions_pruned", 0) for p in plans
    )
    assert pruned_total == counters.get("merge.partitions_pruned", 0)

    # cascade windows tile the run: per-plan deltas sum to the set totals
    cascade = eng.pset.flush_cascade_stats()
    for key, total in (
        ("prefilter_seen", cascade["prefilter_seen"]),
        ("prefilter_dropped", cascade["prefilter_dropped"]),
        ("bf16_resolved", cascade["bf16_resolved"]),
    ):
        assert sum(p["cascade"][key] for p in plans) == total, key
    if d == 2:
        assert cascade["prefilter_enabled"] is False
        assert all(p["tree"] is None for p in plans)

    # the final answer must equal the independent host oracle over the
    # whole stream, and the published digest must be the serve scheme's —
    # the same comparisons the audit plane runs online (conftest helpers)
    from skyline_tpu.audit import canonical_rows

    from conftest import host_oracle, points_digest_of

    _, x = _make_stream(dist, d, 1200, seed=11)
    final = np.asarray(results[-1]["skyline_points"], dtype=np.float32)
    assert canonical_rows(final).tobytes() == host_oracle(x).tobytes()
    snap = eng.snapshots.latest()
    assert snap.digest == points_digest_of(snap.points)

    # byte-identity: the identical run with the plane off emits the same
    # answers, point bytes included
    monkeypatch.setenv("SKYLINE_EXPLAIN", "0")
    _, _, results_off = _drive(policy, dist, d, explain=False)
    assert len(results_off) == len(results)
    for a, b in zip(results, results_off):
        assert a["skyline_size"] == b["skyline_size"]
        assert np.asarray(a["skyline_points"]).tobytes() == \
            np.asarray(b["skyline_points"]).tobytes()


# ------------------------------------------------------------ HTTP surfaces


def _mk_plan_doc(version=7, trace="t-x", path="flat"):
    plan = QueryPlan(trace, "q0")
    plan.merge = {"path": path, "cached": False, "dirty": [0], "clean": []}
    plan.publish = {"version": version, "deduped": False,
                    "event_wm_ms": None}
    return plan.to_doc()


def test_statsserver_explain_endpoint():
    tel = Telemetry()
    tel.explain.add(_mk_plan_doc(version=7, trace="t-x"))
    srv = StatsServer(lambda: {}, port=0, telemetry=tel)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, body = _get(f"{base}/explain")
        assert status == 200 and json.loads(body)["trace_id"] == "t-x"
        status, body = _get(f"{base}/explain?version=7")
        assert status == 200
        status, body = _get(f"{base}/explain?trace_id=t-x")
        assert status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/explain?version=99")
        assert ei.value.code == 404
        missing = json.load(ei.value)
        assert missing["ring"]["recorded_total"] == 1  # evicted vs never
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/explain?version=abc")
        assert ei.value.code == 400
        # the query string must not break sibling exact-path routes
        status, _ = _get(f"{base}/healthz?x=1")
        assert status == 200
    finally:
        srv.close()
    # no telemetry hub: /explain answers 404, not 500
    srv = StatsServer(lambda: {}, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/explain")
        assert ei.value.code == 404
    finally:
        srv.close()


@pytest.fixture
def explain_worker(monkeypatch):
    monkeypatch.delenv("SKYLINE_EXPLAIN", raising=False)
    from skyline_tpu.bridge import MemoryBus, SkylineWorker
    from skyline_tpu.bridge.wire import format_trigger, format_tuple_line

    bus = MemoryBus()
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=2, dims=3), stats_port=0,
        serve_port=0,
    )
    rng = np.random.default_rng(5)
    x = rng.uniform(1, 999, size=(1500, 3)).astype(np.float32)
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(i, row) for i, row in enumerate(x)],
    )
    bus.produce("queries", format_trigger(0, 0))
    while worker.step() > 0:
        pass
    try:
        yield worker
    finally:
        worker.close()


def test_serve_plane_inline_explain_and_byte_stability(explain_worker):
    base = f"http://127.0.0.1:{explain_worker.serve_server.port}"
    _, plain1 = _get(f"{base}/skyline")
    status, ebody = _get(f"{base}/skyline?explain=1")
    assert status == 200
    edoc = json.loads(ebody)
    plan = edoc["explain"]
    assert plan["merge"]["path"] and plan["publish"]["version"] == 1
    assert plan["publish"]["event_wm_ms"] is not None  # real watermark
    # plain reads stay byte-stable around an explain read: same cached
    # prefix, explain only ever rides the volatile tail
    _, plain2 = _get(f"{base}/skyline")
    d1, d2 = json.loads(plain1), json.loads(plain2)
    assert "explain" not in d1 and "explain" not in d2
    assert plain1.split(b', "age_ms"')[0] == plain2.split(b', "age_ms"')[0]
    assert d1["digest"] == d2["digest"] == edoc["digest"]
    # the serve plane's own /explain endpoint answers too
    status, body = _get(f"{base}/explain?version=1")
    assert status == 200
    assert json.loads(body)["trace_id"] == plan["trace_id"]
    try:
        _get(f"{base}/explain?version=999")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404 and "ring" in json.load(e)


def test_worker_metrics_export_explain_counter(explain_worker, prom_parse):
    base = f"http://127.0.0.1:{explain_worker.stats_server.port}"
    _, body = _get(f"{base}/metrics")
    series = prom_parse(body.decode())
    series.pop("__types__")
    assert series["skyline_explain_records_total"][0][1] >= 1.0
    assert series["skyline_explain_depth"] == [({}, 1.0)]
    stats = explain_worker.stats()
    assert stats["explain"]["recorded_total"] == 1


# --------------------------------------------------------------------- CLI


def _run_cli(args, stdin=None):
    return subprocess.run(
        [sys.executable, "-m", "skyline_tpu.explain"] + args,
        capture_output=True, text=True, timeout=60, input=stdin,
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_cli_pretty_print_diff_and_errors(tmp_path):
    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    pa.write_text(json.dumps(_mk_plan_doc(version=1, path="tree")))
    pb.write_text(json.dumps(_mk_plan_doc(version=2, path="cache_hit")))
    r = _run_cli([str(pa)])
    assert r.returncode == 0 and "merge path=tree" in r.stdout
    r = _run_cli([str(pa), "--json"])
    assert json.loads(r.stdout)["merge"]["path"] == "tree"
    r = _run_cli([str(pa), str(pb)])
    assert r.returncode == 0 and "'tree' -> 'cache_hit'" in r.stdout
    r = _run_cli([str(pa), str(pb), "--json"])
    rows = json.loads(r.stdout)
    assert {"field": "merge.path", "a": "tree", "b": "cache_hit"} in rows
    # stdin + wrapper unwrap: a /skyline?explain=1 body is accepted
    wrapper = json.dumps({"version": 1, "explain": _mk_plan_doc()})
    r = _run_cli(["-"], stdin=wrapper)
    assert r.returncode == 0 and "merge path=flat" in r.stdout
    # a JSON doc with no plan inside is a clean error, not a traceback
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"hello": 1}))
    r = _run_cli([str(bad)])
    assert r.returncode != 0 and "no plan found" in r.stderr
