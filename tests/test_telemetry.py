"""Telemetry plane unit tests: histograms, spans, Prometheus rendering,
trace-id propagation through the engine, and the collector's CSV contract
(header race + optional TraceID column)."""

import csv
import json
import threading

import numpy as np
import pytest

from skyline_tpu.metrics.collector import (
    CSV_HEADERS,
    append_result_row,
)
from skyline_tpu.stream.engine import EngineConfig, SkylineEngine
from skyline_tpu.telemetry import (
    Histogram,
    SpanRecorder,
    Telemetry,
    flatten_gauges,
    mint_trace_id,
    render_prometheus,
)
from tests.conftest import parse_prometheus_text


# ---------------------------------------------------------------- histogram


def test_histogram_small_sample_quantiles_exact():
    # below sample_cap the quantiles are true order statistics — identical
    # to np.percentile(..., interpolation='linear'), which bench.py used
    rng = np.random.default_rng(3)
    vals = rng.uniform(0.1, 5000.0, size=200)
    h = Histogram("t")
    h.observe_many(vals)
    for q in (0, 5, 50, 90, 99, 100):
        assert h.quantile(q / 100.0) == pytest.approx(
            float(np.percentile(vals, q)), rel=1e-12
        )


def test_histogram_bucketed_quantiles_bounded_error():
    # past sample_cap quantiles interpolate inside log buckets (~12% wide)
    rng = np.random.default_rng(4)
    vals = rng.lognormal(mean=2.0, sigma=1.0, size=20_000)
    h = Histogram("t", sample_cap=64)
    h.observe_many(vals)
    for q in (50, 90, 99):
        exact = float(np.percentile(vals, q))
        est = h.quantile(q / 100.0)
        assert abs(est - exact) / exact < 0.15, (q, est, exact)
    assert h.count == 20_000
    assert h.quantile(0.0) >= float(vals.min())
    assert h.quantile(1.0) == pytest.approx(float(vals.max()))


def test_histogram_empty_and_validation():
    h = Histogram("t")
    assert h.quantile(0.5) == 0.0
    assert h.snapshot() == {"count": 0}
    with pytest.raises(ValueError):
        h.quantile(1.5)
    with pytest.raises(ValueError):
        Histogram("bad", edges=(1.0, 1.0))


def test_histogram_thread_safety():
    h = Histogram("t", sample_cap=128)
    n_threads, per = 8, 5_000

    def work(seed):
        r = np.random.default_rng(seed)
        for v in r.uniform(0.5, 100.0, size=per):
            h.observe(v)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.count == n_threads * per
    # every observation landed in exactly one bucket
    assert h.bucket_counts()[-1] == (float("inf"), n_threads * per)


def test_histogram_snapshot_fields():
    h = Histogram("t")
    h.observe_many([1.0, 2.0, 3.0, 4.0])
    s = h.snapshot()
    assert s["count"] == 4
    assert s["min"] == 1.0 and s["max"] == 4.0
    assert s["mean"] == pytest.approx(2.5)
    assert {"p50", "p90", "p99"} <= set(s)


# -------------------------------------------------------------------- spans


def test_span_ring_bounded_and_ordered():
    rec = SpanRecorder(capacity=8)
    for i in range(20):
        rec.record(f"s{i}", i * 10, i * 10 + 5)
    spans = rec.snapshot()
    assert len(spans) == 8
    assert [s["name"] for s in spans] == [f"s{i}" for i in range(12, 20)]
    assert rec.recorded == 20


def test_span_chrome_export_schema():
    rec = SpanRecorder()
    with rec.span("phase_a", trace_id="t-1", rows=5):
        pass
    rec.record("phase_b", 100, 250, tid=3)
    doc = rec.to_chrome()
    assert set(doc) == {"traceEvents", "displayTimeUnit", "otherData"}
    assert len(doc["traceEvents"]) == 2
    for e in doc["traceEvents"]:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0
        assert {"name", "pid", "tid", "cat", "args"} <= set(e)
    a = doc["traceEvents"][0]
    assert a["args"] == {"rows": 5, "trace_id": "t-1"}
    json.dumps(doc)  # must be JSON-serializable as-is


def test_span_write_chrome(tmp_path):
    rec = SpanRecorder()
    rec.record("x", 0, 1000)
    out = tmp_path / "trace.json"
    assert rec.write_chrome(str(out)) == 1
    doc = json.loads(out.read_text())
    assert doc["traceEvents"][0]["name"] == "x"


def test_mint_trace_id_unique_across_threads():
    seen = []
    lock = threading.Lock()

    def mint_many():
        ids = [mint_trace_id() for _ in range(500)]
        with lock:
            seen.extend(ids)

    ts = [threading.Thread(target=mint_many) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(set(seen)) == len(seen) == 2000


# --------------------------------------------------------------- prometheus


def test_render_prometheus_parses(prom_parse):
    h = Histogram("lat_ms")
    h.observe_many([0.5, 2.0, 700.0])
    text = render_prometheus(
        counters={"reads_served": 7},
        gauges={"depth": 3, "ratio": 0.5},
        histograms=[h],
    )
    series = prom_parse(text)
    types = series.pop("__types__")
    assert types["skyline_reads_served_total"] == "counter"
    assert types["skyline_lat_ms"] == "histogram"
    assert series["skyline_reads_served_total"] == [({}, 7.0)]
    assert series["skyline_depth"] == [({}, 3.0)]
    buckets = series["skyline_lat_ms_bucket"]
    # cumulative and +Inf-terminated
    assert buckets[-1][0]["le"] == "+Inf" and buckets[-1][1] == 3.0
    counts = [v for _, v in buckets]
    assert counts == sorted(counts)
    assert series["skyline_lat_ms_count"] == [({}, 3.0)]


def test_flatten_gauges_nested():
    flat = flatten_gauges(
        {
            "a": 1,
            "nested": {"x": 2.5, "deep": {"y": 3}},
            "flag": True,
            "skip_str": "text",
            "skip_list": [1, 2],
            "skip_none": None,
        }
    )
    assert flat == {"a": 1, "nested_x": 2.5, "nested_deep_y": 3, "flag": 1}


def test_telemetry_hub_get_or_create():
    tel = Telemetry()
    h1 = tel.histogram("x")
    h2 = tel.histogram("x")
    assert h1 is h2
    tel.counters.inc("evt")
    text = tel.render_prometheus(gauges={"g": 1}, extra_counters={"extra": 2})
    series = parse_prometheus_text(text)
    assert series["skyline_evt_total"] == [({}, 1.0)]
    assert series["skyline_extra_total"] == [({}, 2.0)]


# --------------------------------------------- engine trace-id propagation


def _run_traced_query(with_store: bool):
    tel = Telemetry()
    eng = SkylineEngine(EngineConfig(parallelism=2, dims=2), telemetry=tel)
    store = None
    if with_store:
        from skyline_tpu.serve import SnapshotStore

        store = SnapshotStore()
        eng.attach_snapshots(store)
    rng = np.random.default_rng(0)
    ids = np.arange(1, 201, dtype=np.int64)
    vals = rng.uniform(1, 999, size=(200, 2)).astype(np.float32)
    eng.process_records(ids, vals)
    eng.process_trigger("q1,0")
    (result,) = eng.poll_results()
    return tel, store, result


def test_engine_trace_id_propagation():
    tel, store, result = _run_traced_query(with_store=True)
    tid = result["trace_id"]
    assert tid and "-" in tid
    # the published snapshot carries the same correlation key
    assert store.latest().meta["trace_id"] == tid
    names = {s["name"] for s in tel.spans.snapshot()}
    assert {"ingest", "local", "merge", "publish", "query"} <= names
    # every query-scoped span is stamped with the query's trace id
    for s in tel.spans.snapshot():
        if s["name"] in ("local", "merge", "publish", "query"):
            assert s.get("trace_id") == tid, s
    assert tel.histogram("query_latency_ms").count == 1
    assert tel.histogram("global_merge_ms").count == 1
    assert tel.histogram("ingest_batch_ms").count == 1


def test_engine_without_telemetry_unchanged():
    eng = SkylineEngine(EngineConfig(parallelism=2, dims=2))
    rng = np.random.default_rng(0)
    eng.process_records(
        np.arange(1, 101, dtype=np.int64),
        rng.uniform(1, 999, size=(100, 2)).astype(np.float32),
    )
    eng.process_trigger("q1,0")
    (result,) = eng.poll_results()
    assert "trace_id" not in result


def test_sliding_engine_trace_id():
    from skyline_tpu.stream.sliding_engine import SlidingEngine

    tel = Telemetry()
    eng = SlidingEngine(
        EngineConfig(parallelism=2, dims=2),
        window_size=100,
        slide=50,
        telemetry=tel,
    )
    rng = np.random.default_rng(0)
    eng.process_records(
        np.arange(100, dtype=np.int64),
        rng.uniform(1, 999, size=(100, 2)).astype(np.float32),
    )
    eng.process_trigger("w1,0")
    (result,) = eng.poll_results()
    assert result["trace_id"]
    names = {s["name"] for s in tel.spans.snapshot()}
    assert {"ingest", "merge", "query"} <= names
    assert tel.histogram("query_latency_ms").count == 1


# ----------------------------------------------------------- collector CSV


def test_collector_header_race_two_threads(tmp_path):
    # regression: both writers once saw "no file" and both wrote the header
    path = str(tmp_path / "out.csv")
    barrier = threading.Barrier(2)
    rows_per = 50

    def writer(qid):
        barrier.wait()
        for i in range(rows_per):
            append_result_row(
                path, {"query_id": f"{qid}-{i}", "skyline_size": i}
            )

    ts = [threading.Thread(target=writer, args=(q,)) for q in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    assert rows[0] == CSV_HEADERS
    assert sum(1 for r in rows if r == CSV_HEADERS) == 1
    assert len(rows) == 1 + 2 * rows_per


def test_collector_without_trace_id_byte_stable(tmp_path):
    # untraced results keep the reference 10-column shape byte-for-byte
    a, b = str(tmp_path / "a.csv"), str(tmp_path / "b.csv")
    data = {"query_id": "q", "skyline_size": 3, "query_latency_ms": 1.5}
    append_result_row(a, data)
    append_result_row(b, dict(data))  # same payload, fresh file
    assert open(a, "rb").read() == open(b, "rb").read()
    with open(a, newline="") as f:
        rows = list(csv.reader(f))
    assert rows[0] == CSV_HEADERS
    assert len(rows[1]) == len(CSV_HEADERS)
    assert "TraceID" not in rows[0]


def test_collector_with_trace_id_column(tmp_path):
    path = str(tmp_path / "out.csv")
    append_result_row(
        path, {"query_id": "q1", "skyline_size": 3, "trace_id": "abc-1"}
    )
    append_result_row(
        path, {"query_id": "q2", "skyline_size": 4, "trace_id": "abc-2"}
    )
    with open(path, newline="") as f:
        rows = list(csv.reader(f))
    assert rows[0] == CSV_HEADERS + ["TraceID"]
    assert rows[1][-1] == "abc-1" and rows[2][-1] == "abc-2"
