"""Mesh-sharded streaming engine: same results as single-device, any mesh.

The reference simulates distribution with a local mini-cluster (SURVEY.md §4
item 5); here the SAME SkylineEngine runs its stacked partition state sharded
over a virtual 8-device mesh — flushes SPMD, global merge as the sharded
two-phase collective — and must be bit-identical on results to the
single-device engine (device placement is not query semantics).
"""

import numpy as np
import pytest

from skyline_tpu.parallel.mesh import make_mesh
from skyline_tpu.stream import EngineConfig, SkylineEngine
from conftest import assert_same_set


def _run(cfg, mesh, x, chunks=5):
    eng = SkylineEngine(cfg, mesh=mesh)
    ids = np.arange(x.shape[0])
    step = -(-x.shape[0] // chunks)
    for i in range(0, x.shape[0], step):
        eng.process_records(ids[i : i + step], x[i : i + step])
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    return r


@pytest.mark.parametrize("n_dev", [2, 8])
@pytest.mark.parametrize("algo", ["mr-dim", "mr-grid", "mr-angle"])
def test_meshed_engine_matches_single_device(rng, n_dev, algo):
    cfg = EngineConfig(
        parallelism=4, algo=algo, dims=3, domain_max=1000.0,
        buffer_size=256, emit_skyline_points=True,
    )
    x = rng.uniform(0, 1000, size=(4000, 3)).astype(np.float32)
    r_plain = _run(cfg, None, x)
    r_mesh = _run(cfg, make_mesh(n_dev), x)
    assert r_mesh["skyline_size"] == r_plain["skyline_size"]
    assert r_mesh["optimality"] == pytest.approx(r_plain["optimality"])
    assert_same_set(r_mesh["skyline_points"], r_plain["skyline_points"])


def test_meshed_engine_rejects_indivisible_partitions():
    cfg = EngineConfig(parallelism=3, dims=2)  # 6 partitions on 8 devices
    with pytest.raises(ValueError, match="divisible"):
        SkylineEngine(cfg, mesh=make_mesh(8))


def test_checkpoint_across_topologies(rng, tmp_path):
    """Save on a mesh, restore without one (and vice versa): placement is
    runtime state, results must agree."""
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    cfg = EngineConfig(parallelism=4, algo="mr-angle", dims=2,
                       domain_max=100.0, buffer_size=128)
    x = rng.uniform(0, 100, size=(2000, 2)).astype(np.float32)
    eng = SkylineEngine(cfg, mesh=make_mesh(8))
    eng.process_records(np.arange(1000), x[:1000])
    path = str(tmp_path / "ck.npz")
    save_engine(eng, path)

    restored = load_engine(path)  # no mesh
    assert restored.mesh is None
    for e in (eng, restored):
        e.process_records(np.arange(1000, 2000), x[1000:])
        e.process_trigger("0,0")
    (r_mesh,) = eng.poll_results()
    (r_plain,) = restored.poll_results()
    assert r_mesh["skyline_size"] == r_plain["skyline_size"]


def test_meshed_engine_custom_axis_name(rng):
    """A mesh whose first axis is not named 'p' must work end to end
    (ingest AND the query-time sharded global merge)."""
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("workers",))
    cfg = EngineConfig(parallelism=4, algo="mr-grid", dims=2,
                       domain_max=100.0, buffer_size=64)
    x = rng.uniform(0, 100, size=(1500, 2)).astype(np.float32)
    r_mesh = _run(cfg, mesh, x)
    r_plain = _run(cfg, None, x)
    assert r_mesh["skyline_size"] == r_plain["skyline_size"]


@pytest.mark.parametrize("n_dev", [2, 8])
@pytest.mark.parametrize("algo", ["mr-dim", "mr-angle"])
def test_meshed_lazy_policy_matches_single_device(rng, n_dev, algo):
    """The lazy (SFS-at-query) policy under a mesh — shard_map rounds over
    the partition axis — must produce the single-device engine's exact
    result set, balanced or skewed (mr-angle at 3D skews the routing)."""
    cfg = EngineConfig(
        parallelism=4, algo=algo, dims=3, domain_max=1000.0,
        flush_policy="lazy", emit_skyline_points=True,
    )
    x = rng.uniform(0, 1000, size=(4000, 3)).astype(np.float32)
    r_plain = _run(cfg, None, x)
    r_mesh = _run(cfg, make_mesh(n_dev), x)
    assert r_mesh["skyline_size"] == r_plain["skyline_size"]
    assert_same_set(r_mesh["skyline_points"], r_plain["skyline_points"])
    assert r_mesh["optimality"] == pytest.approx(r_plain["optimality"])


def test_meshed_lazy_sequential_queries(rng):
    """Second query under meshed lazy exercises the meshed sfs_cleanup
    (non-empty initial state)."""
    cfg = EngineConfig(
        parallelism=4, algo="mr-dim", dims=2, domain_max=1000.0,
        flush_policy="lazy", emit_skyline_points=True,
    )
    mesh = make_mesh(4)
    eng = SkylineEngine(cfg, mesh=mesh)
    a = rng.uniform(0, 1000, size=(1500, 2)).astype(np.float32)
    b = rng.uniform(0, 1000, size=(1500, 2)).astype(np.float32)
    ids = np.arange(3000)
    eng.process_records(ids[:1500], a)
    eng.process_trigger("0,0")
    (r1,) = eng.poll_results()
    eng.process_records(ids[1500:], b)
    eng.process_trigger("1,0")
    (r2,) = eng.poll_results()
    from skyline_tpu.ops.dominance import skyline_np

    assert_same_set(r1["skyline_points"], skyline_np(a))
    assert_same_set(r2["skyline_points"], skyline_np(np.concatenate([a, b])))


def test_meshed_lazy_capacity_growth_and_checkpoint(rng, tmp_path):
    """Meshed lazy must survive capacity growth of the sharded buffers
    mid-flush and a checkpoint/restore onto the same mesh."""
    from skyline_tpu.ops.dominance import skyline_np
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    n, d = 6000, 3
    x = np.abs(1500 - rng.uniform(0, 1000, (n, d))).astype(np.float32)
    cfg = EngineConfig(parallelism=4, algo="mr-dim", dims=d,
                      domain_max=2000.0, flush_policy="lazy",
                      emit_skyline_points=True)
    mesh = make_mesh(8)
    want = skyline_np(x)
    ids = np.arange(n)

    eng = SkylineEngine(cfg, mesh=mesh)
    for i in range(0, n, 1000):
        eng.process_records(ids[i : i + 1000], x[i : i + 1000])
    eng.process_trigger("0,0")
    (r,) = eng.poll_results()
    assert r["skyline_size"] == want.shape[0]
    assert eng.pset._cap > 1024  # growth actually exercised

    eng2 = SkylineEngine(cfg, mesh=mesh)
    eng2.process_records(ids[:3000], x[:3000])
    path = str(tmp_path / "meshed_lazy.npz")
    save_engine(eng2, path)
    eng3 = load_engine(path, mesh=mesh)
    eng3.process_records(ids[3000:], x[3000:])
    eng3.process_trigger("0,0")
    (r3,) = eng3.poll_results()
    assert r3["skyline_size"] == want.shape[0]
    assert_same_set(r3["skyline_points"], want)
