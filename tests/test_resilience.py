"""Crash safety: WAL framing, deterministic fault injection, checkpoint
fallback, supervised restart — and the chaos property.

The acceptance test here is ``test_chaos_supervised_equals_uninterrupted``:
a supervised worker driven through a deterministic crash schedule (fault
plans over >=3 kill points x 3 stream distributions x d in {2,4,8}) must
produce a final skyline byte-identical to an uninterrupted run of the same
stream, with ``records_in == n`` (no duplicate, no lost tuple) despite the
crashes landing mid-ingest, mid-fsync, mid-checkpoint-rename.
"""

import json
import os
import signal
import urllib.error
import urllib.request

import numpy as np
import pytest

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.resilience import ResilienceConfig, WAL_SUBDIR
from skyline_tpu.resilience.checkpoints import CheckpointManager
from skyline_tpu.resilience.faults import (
    FaultPlan,
    InjectedCrash,
    active_plan,
    clear,
    fault_point,
    install_from_env,
    install_plan,
)
from skyline_tpu.resilience.supervisor import RestartBudgetExceeded, Supervisor
from skyline_tpu.resilience.wal import (
    WalWriter,
    batch_digest,
    list_segments,
    read_records,
    rows_from_b64,
    rows_to_b64,
)
from skyline_tpu.stream import EngineConfig
from skyline_tpu.telemetry import Telemetry
from skyline_tpu.workload.generators import anti_correlated, correlated, uniform

from conftest import assert_same_set


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    """Every test starts and ends with no fault plan installed."""
    clear()
    yield
    clear()


def _feed(bus, rows, start_id=0):
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(start_id + i, row) for i, row in enumerate(rows)],
    )


# --------------------------------------------------------------------------
# WAL framing
# --------------------------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    d = str(tmp_path / "wal")
    w = WalWriter(d, fsync="off")
    recs = [
        {"type": "start", "data_off": 0, "query_off": 0},
        {"type": "batch", "lo": 0, "hi": 64, "digest": "aa"},
        {"type": "commit", "data_off": 64, "query_off": 1},
    ]
    for r in recs:
        w.append(r)
    w.close()
    got, torn = read_records(d)
    assert got == recs
    assert torn == 0


def test_wal_fresh_segment_per_writer(tmp_path):
    d = str(tmp_path / "wal")
    w1 = WalWriter(d, fsync="off")
    w1.append({"type": "start"})
    w1.close()
    w2 = WalWriter(d, fsync="off")
    w2.append({"type": "commit"})
    w2.close()
    # second writer never appends into the first writer's (possibly torn)
    # segment
    assert [seq for seq, _ in list_segments(d)] == [1, 2]
    got, torn = read_records(d)
    assert [r["type"] for r in got] == ["start", "commit"]
    assert torn == 0


def test_wal_torn_tail_stops_cleanly(tmp_path):
    d = str(tmp_path / "wal")
    w = WalWriter(d, fsync="off")
    w.append({"type": "batch", "lo": 0, "hi": 10, "digest": "x"})
    w.append({"type": "commit", "data_off": 10, "query_off": 0})
    w.close()
    _, path = list_segments(d)[-1]
    # tear the last frame mid-payload (a crashed os.write)
    with open(path, "rb") as f:
        data = f.read()
    with open(path, "wb") as f:
        f.write(data[:-5])
    got, torn = read_records(d)
    assert [r["type"] for r in got] == ["batch"]
    assert torn == 1


def test_wal_crc_mismatch_stops_replay(tmp_path):
    d = str(tmp_path / "wal")
    w = WalWriter(d, fsync="off")
    w.append({"type": "batch", "lo": 0, "hi": 10, "digest": "x"})
    w.append({"type": "commit", "data_off": 10, "query_off": 0})
    w.close()
    _, path = list_segments(d)[-1]
    with open(path, "r+b") as f:
        data = f.read()
        # flip one byte inside the FIRST frame's payload: nothing after the
        # corruption may be trusted, even if physically intact
        f.seek(len(b"SKWL1\n") + 8 + 2)
        f.write(bytes([data[len(b"SKWL1\n") + 8 + 2] ^ 0xFF]))
    got, torn = read_records(d)
    assert got == []
    assert torn == 1


def test_wal_rotation_and_barrier_truncation(tmp_path):
    d = str(tmp_path / "wal")
    telem = Telemetry()
    w = WalWriter(d, segment_bytes=64, fsync="off", telemetry=telem)
    for i in range(20):
        w.append({"type": "commit", "data_off": i, "query_off": 0})
    assert w.segments_created > 1  # 64-byte segments force rotation
    w.barrier({"type": "ckpt", "data_off": 20, "query_off": 0})
    w.append({"type": "commit", "data_off": 21, "query_off": 0})
    w.close()
    # after the barrier the WAL's whole content is the ckpt record plus
    # everything after it — older segments are gone
    got, torn = read_records(d)
    assert torn == 0
    assert [r["type"] for r in got] == ["ckpt", "commit"]
    assert w.segments_truncated > 0
    assert telem.counters.snapshot()["wal.truncated"] == w.segments_truncated


def test_wal_rows_b64_roundtrip(rng):
    rows = rng.random((7, 3)).astype(np.float32)
    back = rows_from_b64(rows_to_b64(rows), 3)
    np.testing.assert_array_equal(rows, back)
    # digest is order- and dtype-sensitive
    ids = np.arange(7, dtype=np.int64)
    assert batch_digest(ids, rows) != batch_digest(ids[::-1], rows)


def test_wal_rejects_bad_fsync_policy(tmp_path):
    with pytest.raises(ValueError, match="fsync"):
        WalWriter(str(tmp_path / "wal"), fsync="sometimes")


# --------------------------------------------------------------------------
# fault plans
# --------------------------------------------------------------------------


def test_fault_plan_parse_and_one_shot():
    plan = FaultPlan.parse("crash@kafka.poll:2,flush.pre_merge:1")
    install_plan(plan)
    fault_point("kafka.poll")  # hit 1: below nth
    with pytest.raises(InjectedCrash):
        fault_point("flush.pre_merge")
    with pytest.raises(InjectedCrash):
        fault_point("kafka.poll")  # hit 2
    # one-shot: the same hit numbers never fire again
    fault_point("kafka.poll")
    fault_point("flush.pre_merge")
    assert plan.exhausted()
    assert plan.hits == {"kafka.poll": 3, "flush.pre_merge": 2}


def test_fault_plan_rejects_garbage():
    with pytest.raises(ValueError, match="unknown kill point"):
        FaultPlan.parse("crash@no.such.point:1")
    with pytest.raises(ValueError, match="action"):
        FaultPlan.parse("melt@kafka.poll:1")
    with pytest.raises(ValueError, match=">= 1"):
        FaultPlan.parse("crash@kafka.poll:0")
    with pytest.raises(ValueError, match="expected action@point:nth"):
        FaultPlan.parse("kafka.poll")
    with pytest.raises(ValueError, match="empty"):
        FaultPlan.parse(" , ")


def test_fault_point_is_noop_without_plan():
    for _ in range(3):
        fault_point("kafka.poll")  # must not raise, must not accumulate


def test_install_from_env_is_parse_once(monkeypatch):
    monkeypatch.setenv("SKYLINE_FAULT_PLAN", "crash@kafka.poll:1")
    plan = install_from_env()
    assert plan is not None and active_plan() is plan
    # an installed plan keeps its counters across worker re-constructions:
    # re-arming must NOT re-parse (each clause kills exactly one incarnation)
    plan.hits["kafka.poll"] = 5
    assert install_from_env() is plan
    assert active_plan().hits["kafka.poll"] == 5


# --------------------------------------------------------------------------
# checkpoint manager: atomic saves, CRC-verified fallback
# --------------------------------------------------------------------------


def _worker(bus, tmp_path, d=2, interval=0.0, serve=False, telem=None,
            buffer_size=128, fsync="batch"):
    res = ResilienceConfig(
        checkpoint_dir=str(tmp_path),
        checkpoint_interval_s=interval,
        wal_fsync=fsync,
    )
    return SkylineWorker(
        bus,
        EngineConfig(parallelism=2, dims=d, domain_max=10000.0,
                     buffer_size=buffer_size, emit_skyline_points=True),
        resilience=res,
        telemetry=telem,
        serve_port=0 if serve else None,
    )


def test_checkpoint_fallback_on_torn_and_corrupt_files(rng, tmp_path):
    bus = MemoryBus()
    _feed(bus, uniform(rng, 200, 2, 0, 10000))
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    p1 = w.checkpoint_now()
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(200 + i, row)
         for i, row in enumerate(uniform(rng, 100, 2, 0, 10000))],
    )
    while w.step(max_records=64):
        pass
    p2 = w.checkpoint_now()
    w.close()
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)

    # tear the newest checkpoint (a crash mid-write that somehow got
    # renamed — e.g. a torn disk); restore must fall back to the older one
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) // 2)
    telem = Telemetry()
    mgr = CheckpointManager(str(tmp_path), telemetry=telem)
    hit = mgr.restore_latest(telemetry=telem)
    assert hit is not None
    engine, meta, path = hit
    assert path == p1
    assert engine.records_in == 200
    assert meta["extra"]["data_off"] == 200
    assert mgr.fallbacks == 1
    counts = telem.counters.snapshot()
    assert counts["checkpoint.fallbacks"] == 1
    assert counts["checkpoint.restored"] == 1


def test_checkpoint_crc_detects_rewritten_content(rng, tmp_path):
    """The content CRC catches corruption the zip container accepts — a
    structurally valid npz whose array bytes changed must refuse to load."""
    bus = MemoryBus()
    _feed(bus, uniform(rng, 100, 2, 0, 10000))
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    p1 = w.checkpoint_now()
    w.close()
    from skyline_tpu.utils.checkpoint import load_engine

    load_engine(p1)  # intact file loads
    with np.load(p1, allow_pickle=False) as z:
        arrays = {k: z[k].copy() for k in z.files}
    key = max(
        (k for k in arrays if k != "__meta__" and arrays[k].size),
        key=lambda k: arrays[k].nbytes,
    )
    arrays[key] = arrays[key] + 1.0  # valid zip, different bytes
    with open(p1, "wb") as f:
        np.savez_compressed(f, **arrays)
    with pytest.raises(ValueError, match="CRC mismatch"):
        load_engine(p1)


def test_crash_before_replace_preserves_previous_checkpoint(rng, tmp_path):
    bus = MemoryBus()
    _feed(bus, uniform(rng, 150, 2, 0, 10000))
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    p1 = w.checkpoint_now()
    install_plan(FaultPlan.parse("crash@checkpoint.pre_replace:1"))
    with pytest.raises(InjectedCrash):
        w.checkpoint_now()
    clear()
    # the interrupted save never renamed its tmp: the previous checkpoint
    # is intact and still the newest loadable one
    mgr = CheckpointManager(str(tmp_path))
    assert [p for _, p in mgr.list()] == [p1]
    hit = mgr.restore_latest()
    assert hit is not None and hit[2] == p1
    # ...and the next successful save sweeps the stray tmp
    w2_path = mgr.save(hit[0], extra_meta={"data_off": 150, "query_off": 0})
    assert os.path.exists(w2_path)
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".npz.tmp")]
    w.close()


def test_checkpoint_retain_prunes_oldest(rng, tmp_path):
    bus = MemoryBus()
    _feed(bus, uniform(rng, 50, 2, 0, 10000))
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    paths = [w.checkpoint_now() for _ in range(5)]
    mgr = w._ckpt_mgr
    assert mgr.retain == 3
    assert [p for _, p in mgr.list()] == paths[-3:]
    w.close()


# --------------------------------------------------------------------------
# supervisor: backoff growth, bounded budget
# --------------------------------------------------------------------------


def test_supervisor_backoff_grows_then_budget_trips():
    telem = Telemetry()
    sleeps = []

    def always_crashes(attempt):
        raise InjectedCrash(f"boom {attempt}")

    sup = Supervisor(
        always_crashes,
        max_restarts=4,
        backoff_base_s=0.5,
        backoff_cap_s=3.0,
        jitter_frac=0.1,
        telemetry=telem,
        sleep=sleeps.append,
    )
    with pytest.raises(RestartBudgetExceeded):
        sup.run()
    assert sup.restarts == 5  # 4 restarts granted + the fatal 5th crash
    assert len(sleeps) == 4
    # exponential growth under the cap, jitter bounded at +10%
    for i, (lo) in enumerate((0.5, 1.0, 2.0, 3.0)):
        hi = min(3.0, lo) * 1.1 + 1e-9
        assert min(3.0, lo) <= sleeps[i] <= hi
    assert telem.counters.snapshot()["resilience.restarts"] == 5
    # the restart counter reaches /metrics under the prometheus name
    text = telem.render_prometheus()
    assert "skyline_resilience_restarts_total 5" in text


def test_supervisor_recovers_and_returns_result():
    state = {"attempts": 0}

    def flaky(attempt):
        state["attempts"] += 1
        if state["attempts"] < 3:
            raise RuntimeError("transient")
        return "done"

    sup = Supervisor(flaky, max_restarts=5, backoff_base_s=0.0,
                     backoff_cap_s=0.0, sleep=lambda s: None)
    assert sup.run() == "done"
    assert sup.restarts == 2
    assert sup.stats()["crashes"] == ["RuntimeError: transient"] * 2


def test_supervisor_lets_operator_intent_through():
    def interrupted(attempt):
        raise KeyboardInterrupt()

    sup = Supervisor(interrupted, max_restarts=5, sleep=lambda s: None)
    with pytest.raises(KeyboardInterrupt):
        sup.run()
    assert sup.restarts == 0  # ^C is not a crash


# --------------------------------------------------------------------------
# the chaos property: supervised == uninterrupted, byte for byte
# --------------------------------------------------------------------------


def _drive_to_result(worker, bus, out, shared, chunk):
    """Step the worker until a result lands on the output topic. The trigger
    is produced once (after the stream drains) and the produced/collected
    state lives in ``shared`` so it survives worker incarnations."""
    idle = 0
    while True:
        if worker.step(max_records=chunk):
            idle = 0
            continue
        if not shared["trigger_sent"]:
            bus.produce("queries", format_trigger(0, 0))
            shared["trigger_sent"] = True
            continue
        shared["lines"].extend(out.poll())
        if shared["lines"]:
            # trigger processing is at-least-once over exactly-once state: a
            # crash between result emission and offset commit re-emits, so
            # the LAST line is the final answer
            return json.loads(shared["lines"][-1])
        idle += 1
        assert idle < 500, "worker went idle without producing a result"


def _run_stream(tmp_path, rows, d, plan_spec, interval, chunk=64):
    """One full run (supervised when plan_spec is set) over a fresh bus.
    Returns (result_doc, final_worker, supervisor, telemetry)."""
    bus = MemoryBus()
    _feed(bus, rows)
    out = bus.consumer("output-skyline", from_beginning=True)
    telem = Telemetry()  # shared across incarnations: counters accumulate
    shared = {"trigger_sent": False, "lines": []}
    holder = {}
    if plan_spec:
        install_plan(FaultPlan.parse(plan_spec))

    def incarnation(attempt):
        # crash model: the previous incarnation is abandoned WITHOUT close()
        # — its WAL frames were single os.write calls, exactly what a killed
        # process leaves behind in the page cache
        w = _worker(bus, tmp_path, d=d, interval=interval, telem=telem)
        holder["w"] = w
        return _drive_to_result(w, bus, out, shared, chunk)

    sup = Supervisor(incarnation, max_restarts=8, backoff_base_s=0.0,
                     backoff_cap_s=0.0, telemetry=telem, sleep=lambda s: None)
    try:
        doc = sup.run()
    finally:
        clear()
        if holder.get("w") is not None:
            holder["w"].close()
    return doc, holder["w"], sup, telem


# >= 3 kill points x 3 distributions x d in {2, 4, 8}; ``interval=0``
# disables periodic checkpoints so recovery is pure WAL replay, a tiny
# interval checkpoints every dirty step so the barrier/truncation/restore
# path is the one exercised
CHAOS_GRID = [
    ("crash@kafka.poll:5", uniform, 2, 0.0),
    ("crash@flush.pre_merge:2", correlated, 4, 0.0),
    ("crash@wal.pre_fsync:3", anti_correlated, 8, 0.0),
    ("crash@checkpoint.pre_replace:2,crash@kafka.poll:9", uniform, 4, 1e-6),
]


@pytest.mark.parametrize("plan,gen,d,interval", CHAOS_GRID)
def test_chaos_supervised_equals_uninterrupted(rng, tmp_path, plan, gen, d,
                                               interval):
    n = 400
    rows = gen(rng, n, d, 0, 10000)
    base_doc, base_w, base_sup, _ = _run_stream(
        tmp_path / "base", rows, d, None, 0.0
    )
    assert base_sup.restarts == 0
    doc, w, sup, telem = _run_stream(tmp_path / "chaos", rows, d, plan,
                                     interval)

    assert sup.restarts >= 1, "the fault plan never fired"
    assert active_plan() is None
    # exactly-once state: every produced tuple ingested exactly once despite
    # the crash schedule
    assert w.engine.records_in == n
    # byte-identity: same skyline, same points, same order
    assert doc["skyline_size"] == base_doc["skyline_size"]
    np.testing.assert_array_equal(
        np.asarray(doc["skyline_points"], dtype=np.float32),
        np.asarray(base_doc["skyline_points"], dtype=np.float32),
    )
    counts = telem.counters.snapshot()
    assert counts["resilience.restarts"] == sup.restarts
    if interval:
        # periodic-checkpoint schedule: recovery went through a restore
        assert counts.get("checkpoint.restored", 0) >= 1
        assert counts.get("checkpoint.saved", 0) >= 1
    else:
        # no checkpoints: recovery is pure WAL replay
        assert counts.get("wal.replayed", 0) >= 1
    rec = w._recovered
    assert rec is not None and rec["wal_records"] > 0


def test_chaos_replay_detects_rewritten_history(rng, tmp_path):
    """A WAL that disagrees with the bus (digest mismatch) must refuse to
    recover rather than silently diverge."""
    bus = MemoryBus()
    rows = uniform(rng, 128, 2, 0, 10000)
    _feed(bus, rows)
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    w._wal.flush(force=True)
    # abandon w (crash model), then rewrite history behind the WAL's back
    bus._topics["input-tuples"][5] = format_tuple_line(5, rows[6])
    from skyline_tpu.resilience.wal import WalReplayError

    with pytest.raises(WalReplayError, match="digest"):
        _worker(bus, tmp_path)
    w.close()


# --------------------------------------------------------------------------
# signals: SIGTERM/SIGINT drain into a final checkpoint
# --------------------------------------------------------------------------


def test_sigterm_checkpoints_and_next_boot_replays_nothing(rng, tmp_path):
    bus = MemoryBus()
    _feed(bus, uniform(rng, 300, 2, 0, 10000))
    telem = Telemetry()
    w = _worker(bus, tmp_path, telem=telem)
    while w.step(max_records=64):
        pass
    assert w._dirty
    w._signal_handler(signal.SIGTERM, None)
    # the loop notices the flag at the top of the next iteration, runs the
    # final checkpoint + forced WAL fsync, closes servers, and returns
    w.run_forever(idle_sleep_s=0.0)
    assert w._closed
    assert telem.counters.snapshot().get("checkpoint.saved", 0) == 1
    recs, torn = read_records(os.path.join(str(tmp_path), WAL_SUBDIR))
    assert torn == 0
    assert recs[-1]["type"] == "ckpt" and recs[-1]["data_off"] == 300

    w2 = _worker(bus, tmp_path)
    assert w2.engine.records_in == 300
    assert w2._recovered["replayed_batches"] == 0  # clean exit: no replay
    assert w2._data_pos == 300
    w2.close()


def test_run_forever_installs_handlers_only_with_resilience(rng, tmp_path):
    bus = MemoryBus()
    w = SkylineWorker(bus, EngineConfig(parallelism=2, dims=2))
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        w.run_forever(idle_sleep_s=0.0, stop_after_idle_s=0.0)
        assert signal.getsignal(signal.SIGTERM) is old_term
        w2 = _worker(bus, tmp_path)
        w2.run_forever(idle_sleep_s=0.0, stop_after_idle_s=0.0)
        assert signal.getsignal(signal.SIGTERM) == w2._signal_handler
        w2.close()
    finally:
        signal.signal(signal.SIGTERM, old_term)
        w.close()


def test_supervisor_cli_forwards_sigterm(tmp_path):
    """Operator shutdown through the production entrypoint: SIGTERM to the
    supervisor CLI must reach the worker child (final-checkpoint drain),
    not orphan it — the supervisor exits 0 with the checkpoint on disk."""
    import subprocess
    import sys
    import time

    from skyline_tpu.bridge.kafkalite.broker import Broker
    from skyline_tpu.bridge.kafkalite.client import KafkaLiteProducer

    broker = Broker(host="127.0.0.1", port=0)
    broker.start()
    rows = anti_correlated(np.random.default_rng(5), 200, 2, 0, 10000)
    prod = KafkaLiteProducer(broker.address)
    for i, r in enumerate(rows):
        prod.send("input-tuples", format_tuple_line(i, r))
    prod.flush()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("SKYLINE_FAULT_PLAN", None)
    log_path = tmp_path / "sup.log"
    with open(log_path, "w") as log_f:
        proc = subprocess.Popen(
            [sys.executable, "-m", "skyline_tpu.resilience.supervisor",
             "--max-restarts", "1", "--",
             "--bootstrap", broker.address, "--parallelism", "2",
             "--dims", "2", "--domain", "10000",
             "--checkpoint-dir", str(tmp_path),
             "--checkpoint-interval-s", "0", "--wal-fsync", "off"],
            env=env, stdout=log_f, stderr=subprocess.STDOUT)
        try:
            wal_dir = tmp_path / WAL_SUBDIR
            deadline = time.time() + 60
            # wait until the worker has consumed something (WAL moving)
            while time.time() < deadline:
                if wal_dir.is_dir() and any(
                    p.stat().st_size > 8 for p in wal_dir.iterdir()
                ):
                    break
                assert proc.poll() is None, log_path.read_text()[-1500:]
                time.sleep(0.1)
            else:
                raise AssertionError(
                    f"worker never ingested: {log_path.read_text()[-1500:]}"
                )
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
            broker.stop()
    log = log_path.read_text()
    assert rc == 0, log[-1500:]
    assert "signal 15 received" in log, log[-1500:]
    # interval 0 = checkpoint only on shutdown, so the file on disk proves
    # the forwarded signal drove the drain
    assert list(tmp_path.glob("ckpt-*.npz")), log[-1500:]


# --------------------------------------------------------------------------
# serving plane survives restarts: snapshot head + delta ring from the WAL
# --------------------------------------------------------------------------


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        body = e.read()
        return e.code, (json.loads(body) if body else {})


def test_serve_plane_restored_from_wal(rng, tmp_path):
    bus = MemoryBus()
    rows = anti_correlated(rng, 400, 2, 0, 10000)
    _feed(bus, rows)
    w1 = _worker(bus, tmp_path, serve=True)
    bus.produce("queries", format_trigger(0, 0))
    while w1.step(max_records=128):
        pass
    head1 = w1._snap_store.latest()
    assert head1 is not None and head1.points.shape[0] > 0
    w1.checkpoint_now()  # barrier inlines the serve head into the WAL

    # publish one more delta AFTER the barrier so restore composes
    # base-snapshot + post-barrier deltas (not just the snapshot)
    _feed(bus, anti_correlated(rng, 100, 2, 0, 10000), start_id=400)
    bus.produce("queries", format_trigger(1, 0))
    while w1.step(max_records=128):
        pass
    head2 = w1._snap_store.latest()
    assert head2.version > head1.version
    w1._wal.flush(force=True)
    w1.close()

    w2 = _worker(bus, tmp_path, serve=True)
    store = w2._snap_store
    assert store.restored and store.latest().version == head2.version
    assert store.latest().watermark_id == head2.watermark_id
    assert_same_set(store.latest().points, head2.points)
    # the ring answers catch-up across the restart: composing head1 with
    # the recovered net delta must land exactly on head2
    catchup = w2._serve_ring.since(head1.version)
    assert catchup is not None
    entered, left, to_version = catchup
    assert to_version == head2.version
    pts = head1.points
    if left.size:
        keep = ~np.isin(
            [r.tobytes() for r in pts], [r.tobytes() for r in left]
        )
        pts = pts[keep]
    if entered.size:
        pts = np.concatenate([pts, entered]) if pts.size else entered
    assert_same_set(pts, head2.points)
    # reads advertise the restored (set-exact, order-approximate) state
    status, doc = _get(
        f"http://127.0.0.1:{w2.serve_server.port}/skyline?points=1"
    )
    assert status == 200 and doc["restored"] is True
    assert_same_set(doc["points"], head2.points)

    # the next LIVE publish clears the flag
    _feed(bus, anti_correlated(rng, 50, 2, 0, 10000), start_id=500)
    bus.produce("queries", format_trigger(2, 0))
    while w2.step(max_records=128):
        pass
    assert not store.restored
    status, doc = _get(f"http://127.0.0.1:{w2.serve_server.port}/skyline")
    assert status == 200 and "restored" not in doc
    w2.close()


def test_event_watermark_survives_restart(rng, tmp_path):
    """Freshness lineage durability (ISSUE 8): the restored serve head
    carries exactly the event watermark the pre-crash worker published
    (checkpoint barrier for the base + WAL ``ewm`` for post-barrier
    deltas), the restored engine's tracker is re-seeded with it, and
    ``staleness_ms`` is monotone non-increasing across the restored ->
    live-publish transition."""
    import time

    bus = MemoryBus()
    _feed(bus, anti_correlated(rng, 300, 2, 0, 10000))
    w1 = _worker(bus, tmp_path, serve=True)
    bus.produce("queries", format_trigger(0, 0))
    while w1.step(max_records=128):
        pass
    w1.checkpoint_now()  # barrier embeds the head (incl. event_wm_ms)
    # a post-barrier publish: restore must take THIS wm from the WAL delta
    _feed(bus, anti_correlated(rng, 100, 2, 0, 10000), start_id=300)
    bus.produce("queries", format_trigger(1, 0))
    while w1.step(max_records=128):
        pass
    head = w1._snap_store.latest()
    wm_live = head.event_wm_ms
    assert wm_live is not None  # worker stamps the poll-time proxy
    assert w1.engine.freshness.stats()["published_wm_ms"] == pytest.approx(
        wm_live
    )
    w1._wal.flush(force=True)
    w1.close()

    w2 = _worker(bus, tmp_path, serve=True)
    store = w2._snap_store
    assert store.restored
    # restored == uninterrupted: the watermark is exactly the one the
    # pre-crash worker published, not re-stamped at restore time
    assert store.latest().event_wm_ms == wm_live
    assert store.stats()["event_watermark_ms"] == wm_live
    assert w2.engine.freshness.stats()["published_wm_ms"] == pytest.approx(
        wm_live
    )
    time.sleep(0.05)  # let the restored head age measurably
    status, doc = _get(f"http://127.0.0.1:{w2.serve_server.port}/skyline")
    assert status == 200 and doc["restored"] is True
    stale_restored = doc["staleness_ms"]
    assert stale_restored >= 40.0  # aged at least through the sleep

    # a live publish advances the watermark monotonically; staleness must
    # not jump up across the restored -> live transition
    _feed(bus, anti_correlated(rng, 50, 2, 0, 10000), start_id=400)
    bus.produce("queries", format_trigger(2, 0))
    while w2.step(max_records=128):
        pass
    assert store.latest().event_wm_ms >= wm_live
    status, doc = _get(f"http://127.0.0.1:{w2.serve_server.port}/skyline")
    assert status == 200 and "restored" not in doc
    assert doc["staleness_ms"] <= stale_restored
    w2.close()


# --------------------------------------------------------------------------
# kafkalite: bounded reconnect — clients survive a broker restart
# --------------------------------------------------------------------------


def test_kafkalite_clients_survive_broker_restart(tmp_path):
    from skyline_tpu.bridge.kafkalite import (
        Broker,
        KafkaLiteConsumer,
        KafkaLiteProducer,
    )
    from skyline_tpu.bridge.kafkalite.client import KafkaLiteConnectionError

    b1 = Broker().start()
    host, port_s = b1.address.split(":")
    port = int(port_s)
    prod = KafkaLiteProducer(b1.address)
    cons = KafkaLiteConsumer("t", b1.address, auto_offset_reset="earliest")
    try:
        for i in range(20):
            prod.send("t", f"m{i}")
        prod.flush()
        got = []
        while len(got) < 10:
            got.extend(cons.poll(max_records=5))
        state = b1.state
        b1.stop()
        # a real broker bounce severs established TCP connections; the
        # in-process stop() leaves daemon handler threads draining them, so
        # sever the transport (socket closed, handle kept) to model the
        # restart faithfully — the next request must hit the retry path
        for cl in (prod, cons):
            cl._conn._sock.close()
        # same port, carried log state — the docker-compose `restart` model
        b2 = Broker(host=host, port=port, state=state).start()
        try:
            for i in range(20, 30):
                prod.send("t", f"m{i}")
            prod.flush()  # producer re-flushes through a reconnect
            while len(got) < 30:
                got.extend(cons.poll(max_records=7))
            # consumer resumed from its offset: in-order, no dup, no loss
            assert got == [f"m{i}" for i in range(30)]
            assert (prod._conn.reconnects + cons._conn.reconnects) >= 1
            c = KafkaLiteConsumer("t", b2.address,
                                  auto_offset_reset="earliest")
            c._conn._retries = 1
            c._conn._backoff_s = 0.0
        finally:
            b2.stop()
    finally:
        prod.close()
        cons.close()
    # with the broker gone for good the retry budget is bounded, not
    # infinite: the loop gives up with a typed connection error
    c._conn._sock.close()
    with pytest.raises(KafkaLiteConnectionError):
        c.poll()
    c.close()


def test_kafkalite_consumer_seek(tmp_path):
    from skyline_tpu.bridge.kafkalite import (
        Broker,
        KafkaLiteConsumer,
        KafkaLiteProducer,
    )

    with Broker() as b:
        prod = KafkaLiteProducer(b.address)
        for i in range(10):
            prod.send("t", f"m{i}")
        prod.flush()
        cons = KafkaLiteConsumer("t", b.address, auto_offset_reset="earliest")
        got = []
        while len(got) < 10:
            got.extend(cons.poll())
        assert cons.position() == 10
        cons.seek(4)  # replay currency: re-read the committed suffix
        assert cons.position() == 4
        again = []
        while len(again) < 6:
            again.extend(cons.poll())
        assert again == [f"m{i}" for i in range(4, 10)]
        prod.close()
        cons.close()


def test_memory_consumer_seek_and_position():
    bus = MemoryBus()
    bus.produce_many("t", [str(i) for i in range(5)])
    c = bus.consumer("t", from_beginning=True)
    assert c.position() == 0
    assert c.poll() == ["0", "1", "2", "3", "4"]
    assert c.position() == 5
    c.seek(2)
    assert c.poll() == ["2", "3", "4"]
    c.seek(-3)  # clamped
    assert c.position() == 0


# --------------------------------------------------------------------------
# config plumbing
# --------------------------------------------------------------------------


def test_resilience_flags_round_trip():
    from skyline_tpu.utils.config import parse_job_args

    cfg = parse_job_args([
        "--checkpoint-dir", "/tmp/ckpt",
        "--checkpoint-interval-s", "7.5",
        "--checkpoint-retain", "5",
        "--wal-fsync", "always",
        "--wal-segment-bytes", "8192",
    ])
    res = cfg.resilience_config()
    assert res == ResilienceConfig(
        checkpoint_dir="/tmp/ckpt",
        checkpoint_interval_s=7.5,
        checkpoint_retain=5,
        wal_fsync="always",
        wal_segment_bytes=8192,
    )


def test_resilience_off_by_default():
    from skyline_tpu.utils.config import parse_job_args

    assert parse_job_args([]).resilience_config() is None


def test_sliding_window_rejects_checkpointing():
    from skyline_tpu.utils.config import parse_job_args

    with pytest.raises(ValueError, match="sliding-window"):
        parse_job_args([
            "--window", "1000", "--slide", "100",
            "--checkpoint-dir", "/tmp/ckpt",
        ])


def test_worker_stats_surface_resilience(rng, tmp_path):
    bus = MemoryBus()
    _feed(bus, uniform(rng, 64, 2, 0, 10000))
    w = _worker(bus, tmp_path)
    while w.step(max_records=64):
        pass
    out = w.stats()["resilience"]
    assert out["data_off"] == 64
    assert out["wal"]["appends"] >= 2  # start + batch/commit records
    assert out["checkpoint"]["directory"] == str(tmp_path)
    w.close()
