"""PartitionSet (stacked, single-launch flush) semantics.

The batched path must be result-identical to per-partition incremental
merging: the merge law (SURVEY.md §4) makes the incremental skyline
batching-invariant, so these tests pin the exact-set equality against the
numpy oracle under uneven routing, heavy skew (multi-round flushes), and
cache invalidation across flush/snapshot interleavings.
"""

import numpy as np

from skyline_tpu.ops.dominance import skyline_np
from skyline_tpu.stream.batched import PartitionSet
from conftest import assert_same_set


def test_uneven_partitions_match_oracle(rng):
    ps = PartitionSet(num_partitions=4, dims=3, buffer_size=64)
    data = [rng.uniform(0, 100, size=(n, 3)).astype(np.float32)
            for n in (5, 700, 33, 0)]
    for p, x in enumerate(data):
        if x.shape[0]:
            ps.add_batch(p, x, max_id=p, now_ms=0.0)
    ps.maybe_flush()
    for p, x in enumerate(data):
        assert_same_set(ps.snapshot(p), skyline_np(x) if x.shape[0] else
                        np.empty((0, 3)))


def test_heavy_skew_multi_round_flush(rng):
    """One partition holding many times buffer_size pending rows exercises
    the multi-round loop inside flush_all."""
    ps = PartitionSet(num_partitions=2, dims=2, buffer_size=1024)
    x = rng.uniform(0, 1000, size=(5000, 2)).astype(np.float32)
    ps.add_batch(0, x, max_id=0, now_ms=0.0)
    ps.add_batch(1, x[:10], max_id=1, now_ms=0.0)
    ps.flush_all()
    assert_same_set(ps.snapshot(0), skyline_np(x))
    assert_same_set(ps.snapshot(1), skyline_np(x[:10]))


def test_snapshot_caches_invalidate_on_new_data(rng):
    ps = PartitionSet(num_partitions=2, dims=2, buffer_size=16)
    a = rng.uniform(0, 100, size=(50, 2)).astype(np.float32)
    ps.add_batch(0, a, max_id=0, now_ms=0.0)
    s1 = ps.snapshot(0)
    assert_same_set(s1, skyline_np(a))
    # a strictly better point must show up in the next snapshot
    better = np.zeros((1, 2), dtype=np.float32)
    ps.add_batch(0, better, max_id=1, now_ms=0.0)
    s2 = ps.snapshot(0)
    assert_same_set(s2, np.zeros((1, 2)))
    # snapshot copies: mutating the returned array must not corrupt state
    s2[:] = 123.0
    assert_same_set(ps.snapshot(0), np.zeros((1, 2)))


def test_incremental_equals_one_shot(rng):
    """Stream in many small chunks == one big batch (batching invariance)."""
    x = rng.uniform(0, 1000, size=(3000, 4)).astype(np.float32)
    ps_stream = PartitionSet(num_partitions=1, dims=4, buffer_size=128)
    for i in range(0, 3000, 77):
        ps_stream.add_batch(0, x[i : i + 77], max_id=i, now_ms=0.0)
        ps_stream.maybe_flush()
    ps_one = PartitionSet(num_partitions=1, dims=4, buffer_size=4096)
    ps_one.add_batch(0, x, max_id=0, now_ms=0.0)
    assert_same_set(ps_stream.snapshot(0), ps_one.snapshot(0))
    assert_same_set(ps_stream.snapshot(0), skyline_np(x))


def test_counts_and_bookkeeping(rng):
    ps = PartitionSet(num_partitions=3, dims=2, buffer_size=32)
    x = rng.uniform(0, 100, size=(100, 2)).astype(np.float32)
    ps.add_batch(1, x, max_id=41, now_ms=7.5)
    assert ps.max_seen_id.tolist() == [-1, 41, -1]
    assert ps.start_time_ms == [None, 7.5, None]
    assert int(ps.records_seen[1]) == 100
    ps.flush_all()
    counts = ps.sky_counts()
    assert counts[0] == 0 and counts[2] == 0
    assert counts[1] == skyline_np(x).shape[0]


def test_initial_capacity_presizing(rng):
    """Pre-sized buffers skip growth and still produce exact skylines."""
    x = rng.uniform(0, 1000, size=(2000, 3)).astype(np.float32)
    ps = PartitionSet(num_partitions=2, dims=3, buffer_size=256,
                      initial_capacity=4096)
    assert ps._cap == 4096
    ps.add_batch(0, x, max_id=0, now_ms=0.0)
    ps.flush_all()
    assert ps._cap == 4096  # no growth happened
    assert_same_set(ps.snapshot(0), skyline_np(x))


def test_meshed_partition_set_matches_oracle(rng):
    """Meshed flushes go through shard_map(vmap(merge)) — result-identical
    to the unmeshed path and to the oracle."""
    from skyline_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(4)
    ps = PartitionSet(num_partitions=8, dims=3, buffer_size=128, mesh=mesh)
    data = [rng.uniform(0, 100, size=(n, 3)).astype(np.float32)
            for n in (5, 700, 33, 0, 257, 64, 1, 900)]
    for p, x in enumerate(data):
        if x.shape[0]:
            ps.add_batch(p, x, max_id=p, now_ms=0.0)
    ps.flush_all()
    for p, x in enumerate(data):
        assert_same_set(ps.snapshot(p), skyline_np(x) if x.shape[0] else
                        np.empty((0, 3)))


def test_meshed_merge_pallas_interpret(rng, monkeypatch):
    """The TPU flush combination — shard_map over vmap over pallas_call —
    lowers and partitions correctly (interpret mode stands in for Mosaic on
    CPU; the hardware path is checked by dryrun_multichip/kernel bench)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from skyline_tpu.ops.dominance import skyline_np as oracle
    from skyline_tpu.parallel.mesh import make_mesh
    from skyline_tpu.stream.window import _MIN_CAP, meshed_merge_step

    monkeypatch.setenv("SKYLINE_PALLAS_INTERPRET", "1")
    mesh = make_mesh(4)
    p_parts, cap, d = 4, _MIN_CAP, 3
    sh = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    sky = jax.device_put(
        np.full((p_parts, cap, d), np.inf, dtype=np.float32), sh)
    sky_valid = jax.device_put(np.zeros((p_parts, cap), dtype=bool), sh)
    batch = np.full((p_parts, cap, d), np.inf, dtype=np.float32)
    bvalid = np.zeros((p_parts, cap), dtype=bool)
    parts = [rng.uniform(0, 100, size=(50, d)).astype(np.float32)
             for _ in range(p_parts)]
    for p, x in enumerate(parts):
        batch[p, :50] = x
        bvalid[p, :50] = True
    merge = meshed_merge_step(mesh, mesh.axis_names[0], True, cap)
    out_sky, out_valid, out_count, _ = merge(
        sky, sky_valid, jax.device_put(batch, sh), jax.device_put(bvalid, sh))
    out_sky = np.asarray(out_sky)
    counts = np.asarray(out_count)
    for p, x in enumerate(parts):
        assert_same_set(out_sky[p, :counts[p]], oracle(x))


def test_lazy_flush_path_choice(rng, monkeypatch):
    """The lazy flush picks per-partition sequential SFS under routing skew
    (P * max_rows > 2 * total_rows) and the one-launch-per-round vmapped SFS
    for balanced loads — and both produce the oracle skyline either way.
    (Device-path heuristic only: the sorted host cascade is pinned off so
    the chooser can't route around both variants — its own engagement is
    covered by tests/test_sorted_sfs.py.)"""
    monkeypatch.setenv("SKYLINE_SORTED_SFS", "off")
    calls = []
    orig_seq = PartitionSet._sfs_sequential
    orig_vm = PartitionSet._sfs_vmapped
    monkeypatch.setattr(
        PartitionSet, "_sfs_sequential",
        lambda self, rows: calls.append("seq") or orig_seq(self, rows))
    monkeypatch.setattr(
        PartitionSet, "_sfs_vmapped",
        lambda self, rows, m: calls.append("vmap") or orig_vm(self, rows, m))

    # skewed: one of 4 partitions holds ~all rows
    ps = PartitionSet(num_partitions=4, dims=3, flush_policy="lazy")
    heavy = rng.uniform(0, 100, size=(4000, 3)).astype(np.float32)
    light = rng.uniform(0, 100, size=(5, 3)).astype(np.float32)
    ps.add_batch(0, heavy, max_id=0, now_ms=0.0)
    ps.add_batch(1, light, max_id=1, now_ms=0.0)
    ps.flush_all()
    assert calls == ["seq"]
    assert_same_set(ps.snapshot(0), skyline_np(heavy))
    assert_same_set(ps.snapshot(1), skyline_np(light))

    # balanced: every partition carries the same load
    calls.clear()
    ps2 = PartitionSet(num_partitions=4, dims=3, flush_policy="lazy")
    parts = [rng.uniform(0, 100, size=(1000, 3)).astype(np.float32)
             for _ in range(4)]
    for p, x in enumerate(parts):
        ps2.add_batch(p, x, max_id=p, now_ms=0.0)
    ps2.flush_all()
    assert calls == ["vmap"]
    for p, x in enumerate(parts):
        assert_same_set(ps2.snapshot(p), skyline_np(x))


def test_sfs_round_single_matches_vmapped(rng):
    """sfs_round_single (skew path) is lane-for-lane identical to the
    vmapped sfs_round on the same sum-sorted blocks."""
    import jax.numpy as jnp

    from skyline_tpu.stream.window import _MIN_CAP, sfs_round, sfs_round_single

    P, B, d, cap = 3, 256, 4, _MIN_CAP
    sky0 = np.full((P, cap, d), np.inf, dtype=np.float32)
    counts0 = np.zeros((P,), dtype=np.int32)
    parts = [rng.uniform(0, 100, size=(2 * B, d)).astype(np.float32)
             for _ in range(P)]
    parts = [x[np.argsort(x.sum(axis=1), kind="stable")] for x in parts]

    sky_v = jnp.asarray(sky0)
    cnt_v = jnp.asarray(counts0)
    singles = [(jnp.asarray(sky0[p]), jnp.asarray(counts0[p]))
               for p in range(P)]
    for rnd in range(2):
        batch = np.stack([x[rnd * B:(rnd + 1) * B] for x in parts])
        bvalid = np.ones((P, B), dtype=bool)
        sky_v, cnt_v, _ = sfs_round(
            sky_v, cnt_v, jnp.asarray(batch), jnp.asarray(bvalid), cap)
        singles = [
            sfs_round_single(s, c, jnp.asarray(batch[p]),
                             jnp.asarray(bvalid[p]), cap)[:2]
            for p, (s, c) in enumerate(singles)]
    cnt_v = np.asarray(cnt_v)
    for p, (s, c) in enumerate(singles):
        assert int(c) == int(cnt_v[p])
        assert_same_set(np.asarray(s)[:int(c)],
                        np.asarray(sky_v)[p, :int(c)])
        # SFS invariant: the appended prefix IS the partition's skyline
        assert_same_set(np.asarray(s)[:int(c)], skyline_np(parts[p]))


def test_global_merge_stats_matches_host_oracle(rng):
    """Device-side union merge (one small stats transfer) returns the same
    per-partition counts, survivor counts, global size, and points as
    merging the pulled snapshots on host — including under skew."""
    ps = PartitionSet(num_partitions=4, dims=3, flush_policy="lazy")
    sizes = (3000, 40, 0, 800)
    parts = [rng.uniform(0, 100, size=(n, 3)).astype(np.float32)
             for n in sizes]
    for p, x in enumerate(parts):
        if x.shape[0]:
            ps.add_batch(p, x, max_id=p, now_ms=0.0)
    ps.flush_all()
    counts, surv, g, pts = ps.global_merge_stats(emit_points=True)

    locals_ = [skyline_np(x) if x.shape[0] else np.empty((0, 3))
               for x in parts]
    union = np.concatenate(locals_, axis=0)
    glob = skyline_np(union)
    assert list(counts) == [s.shape[0] for s in locals_]
    assert g == glob.shape[0]
    assert_same_set(pts, glob)
    # survivors per partition sum to the global count
    assert int(surv.sum()) == g
    for p, loc in enumerate(locals_):
        keep = np.array([any(np.array_equal(r, gr) for gr in glob)
                         for r in loc]) if loc.shape[0] else np.empty(0)
        assert surv[p] == int(keep.sum()) if loc.shape[0] else surv[p] == 0


def test_active_bucket_ladder_invariants():
    """The quarter-pow2 active ladder: always covers n, never exceeds the
    pow2 bucket, stays pow2 while the pow2 bucket is below 16384 (Pallas
    column-tile divisibility), and is a 2048-multiple otherwise."""
    from skyline_tpu.stream.window import _active_bucket, _next_pow2

    for n in [1, 2, 100, 1024, 4097, 16384, 16385, 20480, 20481,
              57000, 100000, 437252, 500001, 1 << 20]:
        b = _active_bucket(n)
        p = _next_pow2(n)
        assert b >= n
        assert b <= p
        if p < 16384:
            assert b == p
        else:
            assert b % 2048 == 0
    # the ladder actually tightens: a survivor count just over a pow2
    # boundary lands on the next quarter step, not the next octave
    assert _active_bucket(262145) == 327680  # 1.25 * 2^18, not 2^19


def test_sequential_sfs_capacity_tracks_survivors_not_rows(rng):
    """The skew-path SFS must size its buffers by actual survivor counts,
    not worst-case streamed rows: a 400k-row skewed stream whose skyline is
    tiny stays in a small capacity bucket (the worst-case pre-grow put a
    10M-row QoS stream into a 16M-row bucket, whose executables crashed
    the remote-compile helper)."""
    ps = PartitionSet(num_partitions=4, dims=3, buffer_size=8192,
                      flush_policy="lazy")
    n = 400_000
    # heavy skew: ~97% of rows to partition 0; uniform data -> tiny skyline
    x = rng.uniform(100, 10000, size=(n, 3)).astype(np.float32)
    ps.add_batch(0, x[: int(n * 0.97)], max_id=0, now_ms=0.0)
    for p in (1, 2, 3):
        ps.add_batch(p, x[int(n * 0.97) + (p - 1) * 4000:
                          int(n * 0.97) + p * 4000], max_id=p, now_ms=0.0)
    ps.flush_all()
    counts = ps.sky_counts()
    assert int(counts.sum()) < 4096  # uniform data: small local skylines
    # capacity stayed near the survivor scale, nowhere near pow2(rows)
    assert ps._cap <= 65536 * 2, ps._cap
    # and the result is still exact
    local0 = np.asarray(ps.sky[0])[: int(counts[0])]
    assert_same_set(local0, skyline_np(x[: int(n * 0.97)]))
