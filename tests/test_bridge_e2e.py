"""End-to-end single-host test: producer lines → MemoryBus → worker → collector CSV."""

import csv
import json

import numpy as np

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.metrics.collector import CSV_HEADERS, collect
from skyline_tpu.ops import skyline_np
from skyline_tpu.stream import EngineConfig
from skyline_tpu.workload.generators import anti_correlated


def test_full_pipeline_over_memory_bus(rng, tmp_path):
    bus = MemoryBus()
    cfg = EngineConfig(parallelism=2, algo="mr-angle", dims=2,
                       domain_max=10000.0, buffer_size=512)
    worker = SkylineWorker(bus, cfg)

    # producer side: stream 5k anti-correlated tuples then a trigger
    x = anti_correlated(rng, 5000, 2, 0, 10000)
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(i, row) for i, row in enumerate(x)],
    )
    # barrier at 4900, not 4999: the id barrier is per-partition (each waits
    # for its OWN max seen id >= N, SURVEY.md §3.3), so a barrier at the very
    # last id only clears on the partition that received that id
    bus.produce("queries", format_trigger(0, 4900))

    # worker drains everything
    while worker.step() > 0:
        pass
    assert worker.results_emitted == 1
    assert bus.size("output-skyline") == 1

    # collector side: CSV row with the reference schema
    out_csv = tmp_path / "run.csv"
    sink = bus.consumer("output-skyline", from_beginning=True)
    n = collect(sink.poll(), str(out_csv), echo=False)
    assert n == 1
    with open(out_csv) as f:
        rows = list(csv.reader(f))
    assert rows[0] == CSV_HEADERS
    row = dict(zip(CSV_HEADERS, rows[1]))
    assert row["QueryID"] == "0"
    assert row["Records"] == "4900"
    assert int(row["SkylineSize"]) == skyline_np(x).shape[0]
    assert float(row["Latency(ms)"]) >= 0  # actually populated (unlike reference)


def test_worker_drops_malformed_and_still_answers(rng):
    bus = MemoryBus()
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=1, algo="mr-dim", dims=2, buffer_size=64)
    )
    bus.produce_many("input-tuples", ["0,5,5", "garbage", "1,3,9", "2,nan,1"])
    bus.produce("queries", format_trigger("q", 1))
    while worker.step() > 0:
        pass
    assert worker.engine.dropped == 2
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["skyline_size"] == 2  # (5,5) and (3,9) are incomparable


def test_query_before_any_data_completes(rng):
    # every partition is at max_seen_id == -1 -> all answer immediately with
    # empty skylines (the reference's empty-partition fast path)
    bus = MemoryBus()
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=2, algo="mr-grid", dims=2, buffer_size=64)
    )
    bus.produce("queries", format_trigger(9, 0))
    while worker.step() > 0:
        pass
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["skyline_size"] == 0
    assert result["optimality"] == 0.0
