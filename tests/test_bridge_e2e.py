"""End-to-end single-host test: producer lines → MemoryBus → worker → collector CSV."""

import csv
import json

import numpy as np

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.metrics.collector import CSV_HEADERS, collect
from skyline_tpu.ops import skyline_np
from skyline_tpu.stream import EngineConfig
from skyline_tpu.workload.generators import anti_correlated


def test_full_pipeline_over_memory_bus(rng, tmp_path):
    bus = MemoryBus()
    cfg = EngineConfig(parallelism=2, algo="mr-angle", dims=2,
                       domain_max=10000.0, buffer_size=512)
    worker = SkylineWorker(bus, cfg)

    # producer side: stream 5k anti-correlated tuples then a trigger
    x = anti_correlated(rng, 5000, 2, 0, 10000)
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(i, row) for i, row in enumerate(x)],
    )
    # barrier at 4900, not 4999: the id barrier is per-partition (each waits
    # for its OWN max seen id >= N, SURVEY.md §3.3), so a barrier at the very
    # last id only clears on the partition that received that id
    bus.produce("queries", format_trigger(0, 4900))

    # worker drains everything
    while worker.step() > 0:
        pass
    assert worker.results_emitted == 1
    assert bus.size("output-skyline") == 1

    # collector side: CSV row with the reference schema
    out_csv = tmp_path / "run.csv"
    sink = bus.consumer("output-skyline", from_beginning=True)
    n = collect(sink.poll(), str(out_csv), echo=False)
    assert n == 1
    with open(out_csv) as f:
        rows = list(csv.reader(f))
    # the worker's telemetry hub stamps a trace_id on every result, so the
    # collector appends its TraceID column (absent for untraced streams —
    # byte-stability covered in tests/test_telemetry.py)
    assert rows[0] == CSV_HEADERS + ["TraceID"]
    row = dict(zip(rows[0], rows[1]))
    assert row["TraceID"]
    assert row["QueryID"] == "0"
    assert row["Records"] == "4900"
    assert int(row["SkylineSize"]) == skyline_np(x).shape[0]
    assert float(row["Latency(ms)"]) >= 0  # actually populated (unlike reference)


def test_worker_drops_malformed_and_still_answers(rng):
    bus = MemoryBus()
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=1, algo="mr-dim", dims=2, buffer_size=64)
    )
    bus.produce_many("input-tuples", ["0,5,5", "garbage", "1,3,9", "2,nan,1"])
    bus.produce("queries", format_trigger("q", 1))
    while worker.step() > 0:
        pass
    assert worker.engine.dropped == 2
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["skyline_size"] == 2  # (5,5) and (3,9) are incomparable


def test_query_before_any_data_completes(rng):
    # every partition is at max_seen_id == -1 -> all answer immediately with
    # empty skylines (the reference's empty-partition fast path)
    bus = MemoryBus()
    worker = SkylineWorker(
        bus, EngineConfig(parallelism=2, algo="mr-grid", dims=2, buffer_size=64)
    )
    bus.produce("queries", format_trigger(9, 0))
    while worker.step() > 0:
        pass
    (line,) = bus.consumer("output-skyline", from_beginning=True).poll()
    result = json.loads(line)
    assert result["skyline_size"] == 0
    assert result["optimality"] == 0.0


def test_worker_step_polls_triggers_before_data_and_applies_after():
    """The premature-empty-result race (a data fetch completing empty just
    before a produce burst whose trigger the later trigger-fetch sees)
    is closed by ordering: triggers are POLLED first and APPLIED after the
    same cycle's data ingest — a visible trigger implies its
    produced-before-it data is fetchable. This pins that ordering."""
    bus = MemoryBus()
    w = SkylineWorker(bus, EngineConfig(parallelism=2, dims=2,
                                        domain_max=100.0))
    events = []

    data_poll = w._data.poll
    query_poll = w._queries.poll
    w._data.poll = lambda *a, **k: (events.append("poll:data"),
                                    data_poll(*a, **k))[1]
    w._queries.poll = lambda *a, **k: (events.append("poll:queries"),
                                       query_poll(*a, **k))[1]
    real_records = w.engine.process_records
    real_trigger = w.engine.process_trigger
    w.engine.process_records = lambda *a, **k: (events.append("records"),
                                                real_records(*a, **k))[1]
    w.engine.process_trigger = lambda t: (events.append("trigger"),
                                          real_trigger(t))[1]

    bus.produce_many("input-tuples", ["0,5,5", "1,3,7", "2,9,1"])
    bus.produce("queries", "0,0")
    w.step()
    # with a trigger pending, the data topic is drained (one extra empty
    # poll) before the trigger is applied
    assert events == ["poll:queries", "poll:data", "records",
                      "poll:data", "trigger"], events
    # step() already drained the result to the output topic
    out = bus.consumer("output-skyline", from_beginning=True).poll()
    assert len(out) == 1
    assert json.loads(out[0])["skyline_size"] == 3  # mutually non-dominated
