"""vmap lowering of the Pallas dominance kernels (interpret mode on CPU).

The batched flush path (stream/batched.py) relies on ``jax.vmap`` of
``pallas_call`` lifting the partition axis into a leading grid dimension.
These tests pin that lowering against a per-item loop so a JAX upgrade or
kernel change that breaks the batching rule fails here, on CPU, rather than
on first TPU contact.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skyline_tpu.ops.pallas_dominance import (
    dominated_by_any_pallas,
    dominated_by_pallas,
)
from skyline_tpu.ops.dominance import skyline_np


@pytest.fixture
def batch(rng):
    P, d, nx, ny = 4, 3, 512, 1024
    xt = jnp.asarray(rng.uniform(0, 100, size=(P, d, nx)).astype(np.float32))
    yt = jnp.asarray(rng.uniform(0, 100, size=(P, d, ny)).astype(np.float32))
    xv = jnp.asarray(rng.random((P, nx)) < 0.8)
    return xt, xv, yt


def test_vmap_rectangular_matches_loop(batch):
    xt, xv, yt = batch
    f = jax.vmap(lambda a, v, b: dominated_by_pallas(a, v, b, interpret=True))
    out = f(xt, xv, yt)
    ref = jnp.stack(
        [
            dominated_by_pallas(xt[p], xv[p], yt[p], interpret=True)
            for p in range(xt.shape[0])
        ]
    )
    assert (out == ref).all()


def test_vmap_self_dominance_matches_oracle(rng):
    P, d, n = 3, 2, 1024
    x = rng.uniform(0, 50, size=(P, n, d)).astype(np.float32)
    f = jax.vmap(
        lambda xt, v: dominated_by_any_pallas(xt, v, interpret=True)
    )
    dom = np.asarray(
        f(jnp.asarray(np.swapaxes(x, 1, 2)), jnp.ones((P, n), dtype=bool))
    )
    for p in range(P):
        keep = ~dom[p]
        sky = skyline_np(x[p])
        assert keep.sum() == sky.shape[0]


# -- rank cascade (ops/pallas_dominance.py rank_transform + rank kernels) ---


@pytest.mark.parametrize("dist", ["uniform", "anti", "ties"])
def test_rank_mask_matches_value_mask_and_oracle(dist, rng):
    from skyline_tpu.ops.pallas_dominance import (
        skyline_mask_pallas,
        skyline_mask_rank_pallas,
    )

    n, d = 1500, 4
    if dist == "uniform":
        x = rng.uniform(0, 1000, (n, d)).astype(np.float32)
    elif dist == "anti":
        base = rng.uniform(0, 1000, (n, 1))
        x = np.abs((1000 - base) + rng.normal(0, 60, (n, d))).astype(
            np.float32
        )
    else:  # heavy duplicates/ties: dense-rank tie semantics must match
        x = rng.uniform(0, 8, (n, d)).round().astype(np.float32)
    valid = rng.random(n) < 0.9
    xd = jnp.asarray(x)
    vd = jnp.asarray(valid)
    mv = np.asarray(skyline_mask_pallas(xd, vd, interpret=True))
    mr = np.asarray(skyline_mask_rank_pallas(xd, vd, interpret=True))
    assert (mv == mr).all()
    want = skyline_np(x[valid])
    assert int(mr.sum()) == want.shape[0]


def test_rank_transform_is_order_embedding(rng):
    from skyline_tpu.ops.pallas_dominance import rank_transform

    n, d = 600, 3
    x = rng.uniform(0, 20, (n, d)).round().astype(np.float32)  # many ties
    valid = np.ones(n, dtype=bool)
    rt = np.asarray(rank_transform(jnp.asarray(x), jnp.asarray(valid)))
    ranks = rt[:d].T  # (n, d)
    assert np.allclose(rt[d], ranks.sum(axis=1))
    for k in range(d):
        a = x[:, k]
        r = ranks[:, k]
        i = rng.integers(0, n, 300)
        j = rng.integers(0, n, 300)
        lt = a[i] < a[j]
        eq = a[i] == a[j]
        assert (r[i][lt] < r[j][lt]).all()
        assert (r[i][eq] == r[j][eq]).all()


def test_rank_sums_exact_past_f32_limit():
    """Rank sums exceed f32's 2^24 exact-integer range at the 8-D/1M flush
    scale; the int32 rank layout must resolve a sum difference of exactly 1
    there (an f32 layout ties and silently keeps the dominated row)."""
    from skyline_tpu.ops.pallas_dominance import dominated_by_rank_pallas

    d, n = 8, 1024
    base = 2_097_152  # per-dim rank ~2^21: rsum ~2^24.03
    rt = np.full((d + 1, n), 0, dtype=np.int32)
    # row 0: dominator with ranks [base]*8; row 1: victim differing by +1
    # in one dim -> rsum differs by exactly 1 at ~16.8M
    rt[:d, 0] = base
    rt[d, 0] = d * base
    rt[:d, 1] = base
    rt[0, 1] = base + 1
    rt[d, 1] = d * base + 1
    assert float(np.float32(d * base)) == float(np.float32(d * base + 1)), (
        "test premise: these sums are indistinguishable in f32"
    )
    valid = np.zeros(n, dtype=bool)
    valid[:2] = True
    dom = np.asarray(
        dominated_by_rank_pallas(
            jnp.asarray(rt), jnp.asarray(valid), jnp.asarray(rt),
            interpret=True,
        )
    )
    assert bool(dom[1]), "victim with rsum+1 must be detected as dominated"
    assert not bool(dom[0])
