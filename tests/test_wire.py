"""Wire-format tests: parsing parity with the reference payloads."""

import json

import numpy as np

from skyline_tpu.bridge.wire import (
    format_result,
    format_trigger,
    format_tuple_line,
    parse_trigger,
    parse_tuple_lines,
)


def test_parse_tuple_lines_roundtrip():
    lines = [format_tuple_line(i, [i * 1.0, i * 2.0]) for i in range(5)]
    ids, vals, dropped = parse_tuple_lines(lines, dims=2)
    assert dropped == 0
    np.testing.assert_array_equal(ids, np.arange(5))
    np.testing.assert_allclose(vals[:, 1], np.arange(5) * 2.0)


def test_parse_tuple_lines_drops_malformed():
    # mirrors ServiceTuple.fromString null-filter (ServiceTuple.java:89-104)
    lines = [
        "1,10,20",
        "garbage",
        "2,10",          # wrong arity
        "3,x,20",        # non-numeric
        "4,nan,20",      # non-finite must not enter windows
        "5,inf,20",
        "6,30,40",
    ]
    ids, vals, dropped = parse_tuple_lines(lines, dims=2)
    assert list(ids) == [1, 6]
    assert dropped == 5


def test_parse_trigger_semantics():
    assert parse_trigger("7,1000000") == ("7", 1000000)
    # count-less payload -> required 0 -> immediate (query_trigger.py:21-26)
    assert parse_trigger("3") == ("3", 0)
    assert parse_trigger("3,notanum") == ("3", 0)
    assert format_trigger(7, 99) == "7,99"


def test_format_result_field_order_and_rounding():
    res = {
        "query_id": "0",
        "record_count": 1000,
        "skyline_size": 42,
        "optimality": 0.123456,
        "ingestion_time_ms": 1,
        "local_processing_time_ms": 2,
        "global_processing_time_ms": 3,
        "total_processing_time_ms": 6,
        "query_latency_ms": 7,
    }
    s = format_result(res)
    parsed = json.loads(s)
    assert parsed["optimality"] == 0.1235  # reference renders %.4f
    assert list(parsed.keys())[:4] == [
        "query_id",
        "record_count",
        "skyline_size",
        "optimality",
    ]
    assert parsed["query_latency_ms"] == 7  # emitted (unlike the reference)


def test_parse_tuple_lines_drops_out_of_range_id():
    # an id beyond int64 must be a dropped line, not an OverflowError
    lines = ["99999999999999999999999,1,2", "1,3,4"]
    ids, vals, dropped = parse_tuple_lines(lines, dims=2)
    assert list(ids) == [1]
    assert dropped == 1


def test_format_result_keeps_extension_fields():
    # partial-result markers must survive wire serialization (the worker
    # emits through format_result)
    s = format_result({"query_id": "1", "skyline_size": 0, "partial": True,
                       "missing_partitions": [0, 3]})
    parsed = json.loads(s)
    assert parsed["partial"] is True
    assert parsed["missing_partitions"] == [0, 3]
