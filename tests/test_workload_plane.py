"""Workload-drift characterization plane (ISSUE 13).

Classification grid over the repo's own stream generators, drift
detection on a mid-stream regime switch (exactly one event), sketch
determinism under a fixed input order, and the byte-identity law: the
plane on or off never changes a published skyline byte.

Generator ground truth caveat (telemetry/workload.py docstring): the
unified ``anti_correlated`` generator's wide epsilon band at d >= 4
produces raw values that genuinely correlate positively (every row
shares one scale factor), so the anti regime at d >= 4 is pinned with
``simple_anti_correlated`` — the exact constant-sum variant whose
anti-correlation survives any dimensionality.
"""

import numpy as np
import pytest

from skyline_tpu.metrics.collector import Counters
from skyline_tpu.stream import EngineConfig, SkylineEngine
from skyline_tpu.telemetry import Telemetry
from skyline_tpu.telemetry.profiler import FlightRecorder
from skyline_tpu.telemetry.workload import WorkloadCharacterizer
from skyline_tpu.workload.generators import generate


def characterize(x, batch=1024, **kw):
    """Feed ``x`` in fixed micro-batches through a small-epoch
    characterizer (4 epochs over 4096 rows at the defaults here)."""
    kw.setdefault("epoch_rows", 1024)
    kw.setdefault("sample_cap", 1024)
    w = WorkloadCharacterizer(int(x.shape[1]), **kw)
    for i in range(0, x.shape[0], batch):
        w.observe(x[i : i + batch])
    return w


def gen(method, d, n=4096, seed=0):
    rng = np.random.default_rng(seed)
    return generate(method, rng, n, d, 0.0, 1000.0).astype(np.float32)


# --------------------------------------------------------------------------
# classification grid
# --------------------------------------------------------------------------


@pytest.mark.parametrize("d", [2, 4, 8])
def test_classifies_uniform(d):
    w = characterize(gen("uniform", d))
    r = w.regime()
    assert r["kind"] == "uniform", r
    # independent dims: the sum-variance ratio sits near its iid value
    assert 0.5 <= w.stats()["epochs"][-1]["sum_ratio"] <= 2.0


@pytest.mark.parametrize("d", [2, 4, 8])
def test_classifies_correlated(d):
    w = characterize(gen("correlated", d))
    r = w.regime()
    assert r["kind"] == "correlated", r
    assert r["rho"] > 0.25


@pytest.mark.parametrize(
    "method,d",
    [
        ("anti_correlated", 2),  # the unified band is tight at d=2
        ("simple_anti_correlated", 4),  # exact constant-sum at d >= 4
        ("simple_anti_correlated", 8),
    ],
)
def test_classifies_anti_correlated(method, d):
    w = characterize(gen(method, d))
    r = w.regime()
    assert r["kind"] == "anti_correlated", (r, w.stats()["epochs"][-1])


def test_regime_unknown_before_first_epoch():
    w = WorkloadCharacterizer(2, epoch_rows=10_000)
    w.observe(gen("uniform", 2, n=512))
    assert w.regime() == {"kind": "unknown", "epoch": 0, "drift_total": 0}


# --------------------------------------------------------------------------
# drift detection
# --------------------------------------------------------------------------


def test_mid_stream_switch_trips_exactly_one_drift():
    # two epochs of uniform, then two of correlated, aligned to the epoch
    # boundary: the flip fires ONCE at the first correlated close; the
    # steady epochs on either side must stay quiet
    w2 = WorkloadCharacterizer(
        4, counters=Counters(), flight=FlightRecorder(64),
        epoch_rows=1024, sample_cap=1024,
    )
    for i in range(2):
        w2.observe(gen("uniform", 4, n=1024, seed=i))
    for i in range(2):
        w2.observe(gen("correlated", 4, n=1024, seed=10 + i))
    st = w2.stats()
    assert st["epochs_closed"] == 4
    assert st["drift_total"] == 1, st["epochs"]
    assert st["kind"] == "correlated"
    assert w2._counters.snapshot()["workload.drift"] == 1
    notes = [e for e in w2._flight.doc()["entries"]
             if e["kind"] == "workload.drift"]
    assert len(notes) == 1
    assert notes[0]["reason"] == "kind_flip"
    assert notes[0]["from"] == "uniform" and notes[0]["to"] == "correlated"


def test_quantile_shift_drift_without_kind_flip():
    w = WorkloadCharacterizer(2, epoch_rows=1024, sample_cap=1024,
                              drift_threshold=0.2)
    rng = np.random.default_rng(3)
    # three epochs in [0, 100), then one shifted to [800, 900): same
    # uniform classification, but the per-dim p50 jumps most of the frozen
    # sketch range
    for _ in range(3):
        w.observe((rng.random((1024, 2)) * 100.0).astype(np.float32))
    w.observe((rng.random((1024, 2)) * 100.0 + 800.0).astype(np.float32))
    st = w.stats()
    assert [e["kind"] for e in st["epochs"]] == ["uniform"] * 4
    assert st["drift_total"] == 1


# --------------------------------------------------------------------------
# determinism + trajectories
# --------------------------------------------------------------------------


def test_sketch_is_deterministic_under_fixed_input_order():
    x = gen("correlated", 4, n=8192)
    a = characterize(x).stats()
    b = characterize(x).stats()
    assert a == b
    # quantiles are real numbers from the frozen-bin sketch (first epoch
    # carries None while the range freezes)
    assert a["epochs"][0]["p50"] is None
    assert all(e["p50"] is not None for e in a["epochs"][1:])


def test_note_query_trajectory_and_dominance_rate():
    w = WorkloadCharacterizer(2, epoch_rows=1024)
    w.note_query(50, 1000)
    w.note_query(25, 1000)
    st = w.stats()
    assert st["dominance_rate"] == pytest.approx(0.975)
    assert st["skyline_size"] == 25
    assert [q["skyline_size"] for q in st["trajectory"]] == [50, 25]


def test_large_batch_is_stride_subsampled():
    w = WorkloadCharacterizer(2, epoch_rows=10_000, sample_cap=128)
    w.observe(gen("uniform", 2, n=4096))
    st = w.stats()
    assert st["rows_seen"] == 4096
    assert st["rows_sampled"] <= 2 * 128  # ceil-stride may slightly exceed


# --------------------------------------------------------------------------
# engine integration: byte identity, /stats, EXPLAIN, Prometheus
# --------------------------------------------------------------------------


def _run(x, telemetry=None):
    cfg = EngineConfig(parallelism=2, dims=x.shape[1], domain_max=1000.0,
                       buffer_size=256, emit_skyline_points=True)
    eng = SkylineEngine(cfg, telemetry=telemetry)
    ids = np.arange(x.shape[0], dtype=np.int64)
    for i in range(0, x.shape[0], 500):
        eng.process_records(ids[i : i + 500], x[i : i + 500])
    eng.process_trigger("q,0")
    (res,) = eng.poll_results()
    return eng, res


def test_engine_byte_identity_with_plane_on_and_off(monkeypatch):
    x = gen("anti_correlated", 2, n=3000)
    monkeypatch.setenv("SKYLINE_WORKLOAD", "0")
    eng_off, off = _run(x)
    assert eng_off.workload is None
    assert "workload" not in eng_off.stats()
    monkeypatch.setenv("SKYLINE_WORKLOAD", "1")
    eng_on, on = _run(x)
    assert eng_on.workload is not None
    assert on["skyline_size"] == off["skyline_size"]
    np.testing.assert_array_equal(
        np.asarray(on["skyline_points"], dtype=np.float32),
        np.asarray(off["skyline_points"], dtype=np.float32),
    )


def test_engine_stats_block_explain_tag_and_metric(monkeypatch):
    monkeypatch.setenv("SKYLINE_WORKLOAD_EPOCH_ROWS", "512")
    hub = Telemetry()
    x = gen("correlated", 2, n=3000)
    eng, _res = _run(x, telemetry=hub)
    assert hub.workload is eng.workload
    st = eng.stats()
    assert st["workload"]["kind"] == "correlated"
    assert st["workload"]["epochs_closed"] >= 2
    plan = hub.explain.latest()
    assert plan["workload"]["kind"] == "correlated"
    body = hub.render_prometheus()
    assert "skyline_workload_drift_total 0" in body
    assert "skyline_workload_epochs_total" in body
