"""Golden-bytes wire-compatibility proof for kafkalite.

Round-2 verdict ("What's missing" #2): the claim that kafkalite speaks the
real Kafka wire protocol rested on the repo's own client talking to its own
broker. kafka-python is not in this image, so these tests pin the frames
against byte sequences derived INDEPENDENTLY from the Kafka protocol spec
(KIP-98 RecordBatch v2; the fixed request header; Produce v3 / Fetch v4
schemas — https://kafka.apache.org/protocol) and against published CRC32C
test vectors (RFC 3720 §B.4), with the checksum recomputed here by a
bit-by-bit implementation that shares no code with the production
slice-by-8 tables. Any byte kafkalite emits differently from a spec
implementation (kafka-python, librdkafka, the real broker) fails here.
"""

import struct

from skyline_tpu.bridge.kafkalite import protocol as P


# -- CRC32C: published known-answer vectors (RFC 3720 §B.4) -----------------

RFC3720_VECTORS = [
    (b"\x00" * 32, 0x8A9136AA),
    (b"\xff" * 32, 0x62A8AB43),
    (bytes(range(32)), 0x46DD794E),
    (bytes(range(31, -1, -1)), 0x113FDB5C),
]


def _crc32c_bitwise(data: bytes) -> int:
    """Independent bit-at-a-time CRC32C (Castagnoli poly, reflected)."""
    crc = 0xFFFFFFFF
    for byte in data:
        crc ^= byte
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
    return crc ^ 0xFFFFFFFF


def test_crc32c_rfc3720_vectors():
    for data, expect in RFC3720_VECTORS:
        assert P.crc32c(data) == expect, data[:4]
        assert _crc32c_bitwise(data) == expect  # the oracle agrees with RFC


def test_crc32c_check_value():
    # the classic CRC "check" input
    assert P.crc32c(b"123456789") == 0xE3069283


# -- RecordBatch v2: hand-assembled golden frame ----------------------------


def _zigzag(v: int) -> bytes:
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _golden_batch(records, base_offset, base_ts):
    """Assemble a RecordBatch v2 with plain struct calls, following KIP-98
    field by field (no kafkalite code)."""
    recs = b""
    for i, (key, value) in enumerate(records):
        body = b"\x00"  # record attributes
        body += _zigzag(0)  # timestampDelta
        body += _zigzag(i)  # offsetDelta
        body += _zigzag(-1) if key is None else _zigzag(len(key)) + key
        body += _zigzag(-1) if value is None else _zigzag(len(value)) + value
        body += _zigzag(0)  # headers
        recs += _zigzag(len(body)) + body
    after_crc = struct.pack(
        ">hiqqqhii",
        0,  # attributes: codec none, create-time
        len(records) - 1,  # lastOffsetDelta
        base_ts,
        base_ts,  # maxTimestamp
        -1,  # producerId
        -1,  # producerEpoch
        -1,  # baseSequence
        len(records),
    ) + recs
    crc = _crc32c_bitwise(after_crc)
    tail = struct.pack(">ibI", -1, 2, crc) + after_crc
    return struct.pack(">qi", base_offset, len(tail)) + tail


def test_record_batch_golden_bytes():
    records = [(None, b"1,42.5,17.25"), (b"k", b"second")]
    got = P.encode_record_batch(records, base_offset=42, base_timestamp=1_700_000_000_000)
    want = _golden_batch(records, 42, 1_700_000_000_000)
    assert got == want  # byte-for-byte


def test_record_batch_decode_golden_bytes():
    # decode a frame built ONLY by the independent assembler
    frame = _golden_batch(
        [(None, b"0,1.0,2.0"), (None, b"1,3.0,4.0")], 7, 123456
    )
    out = P.decode_record_batches(frame)
    assert out == [(7, None, b"0,1.0,2.0"), (8, None, b"1,3.0,4.0")]


def test_record_batch_crc_tamper_detected():
    frame = bytearray(_golden_batch([(None, b"x")], 0, 0))
    frame[-1] ^= 0x01  # flip one payload bit
    try:
        P.decode_record_batches(bytes(frame))
    except ValueError as e:
        assert "CRC" in str(e)
    else:
        raise AssertionError("tampered batch passed CRC check")


# -- request framing: golden header bytes -----------------------------------


def test_request_header_golden_bytes():
    # size + api_key int16 + api_version int16 + correlation_id int32 +
    # client_id nullable string (the non-flexible v1 request header)
    frame = P.encode_request(P.API_PRODUCE, 3, 7, "me", b"BODY")
    want_payload = struct.pack(">hhih", 0, 3, 7, 2) + b"me" + b"BODY"
    assert frame == struct.pack(">i", len(want_payload)) + want_payload


def test_request_header_null_client_id():
    frame = P.encode_request(P.API_FETCH, 4, 1, None, b"")
    want_payload = struct.pack(">hhih", 1, 4, 1, -1)
    assert frame == struct.pack(">i", len(want_payload)) + want_payload


def test_response_header_golden_bytes():
    frame = P.encode_response(99, b"XY")
    assert frame == struct.pack(">ii", 6, 99) + b"XY"


# -- Produce v3 round trip against the spec schema --------------------------


def test_produce_v3_request_body_parses_by_spec():
    """The broker-side parse must accept a Produce v3 body assembled purely
    from the spec schema: transactional_id nullable-str, acks int16,
    timeout int32, [topic [partition records-bytes]]."""
    batch = _golden_batch([(None, b"9,5.5")], 0, 0)
    body = (
        struct.pack(">h", -1)  # transactional_id = null
        + struct.pack(">hi", 1, 30000)  # acks, timeout
        + struct.pack(">i", 1)  # one topic
        + struct.pack(">h", 12) + b"input-tuples"
        + struct.pack(">i", 1)  # one partition entry
        + struct.pack(">i", 0)  # partition 0
        + struct.pack(">i", len(batch)) + batch  # records as BYTES
    )
    r = P.Reader(body)
    assert r.string() is None
    assert r.int16() == 1
    assert r.int32() == 30000

    def read_topic(rr):
        name = rr.string()
        parts = rr.array(
            lambda r2: (r2.int32(), r2.bytes_())
        )
        return name, parts

    topics = r.array(read_topic)
    assert topics[0][0] == "input-tuples"
    pid, records = topics[0][1][0]
    assert pid == 0
    assert P.decode_record_batches(records) == [(0, None, b"9,5.5")]
    assert r.remaining() == 0


def test_count_records_clamps_malformed_headers():
    # negative batchLength must not spin forever; negative numRecords must
    # not count backwards (broker DoS hardening)
    bad_len = b"\x00" * 8 + struct.pack(">i", -12) + b"\x00" * 49
    assert P.count_records(bad_len) == 0
    good = _golden_batch([(None, b"a"), (None, b"b")], 0, 0)
    neg_records = bytearray(good)
    struct.pack_into(">i", neg_records, 57, -5)
    assert P.count_records(bytes(neg_records)) == 0
    assert P.count_records(good) == 2
    # truncated tail after a good batch is ignored, not an error
    assert P.count_records(good + good[:20]) == 2


def test_broker_restamps_every_batch_in_multibatch_set():
    from skyline_tpu.bridge.kafkalite.broker import _PartitionLog

    log = _PartitionLog()
    # a record set of TWO concatenated batches, both claiming baseOffset 0
    blob = _golden_batch([(None, b"r0"), (None, b"r1")], 0, 0) + _golden_batch(
        [(None, b"r2")], 0, 0
    )
    base = log.append(blob)
    assert base == 0 and log.next_offset == 3
    stored = log.read_from(0, 1 << 20)
    assert P.decode_record_batches(stored) == [
        (0, None, b"r0"),
        (1, None, b"r1"),
        (2, None, b"r2"),
    ]
    # appending again continues the offsets monotonically
    log.append(_golden_batch([(None, b"r3")], 0, 0))
    assert [o for o, _, _ in P.decode_record_batches(log.read_from(0, 1 << 20))] == [0, 1, 2, 3]


def test_zigzag_varint_spec_values():
    # spec: zigzag maps 0,-1,1,-2,2 -> 0,1,2,3,4
    for v, wire in [(0, b"\x00"), (-1, b"\x01"), (1, b"\x02"),
                    (-2, b"\x03"), (2, b"\x04"), (300, b"\xd8\x04")]:
        assert P.Writer().varint(v).build() == wire, v
        assert P.Reader(wire).varint() == v
