"""Device-side sorted dominance cascade (ISSUE 18): the jit-safe cascade
must be byte-identical to the quadratic device kernels at every level —
raw mask (concrete AND traced), union keep, engine flush, published
digest — plus the f32 sum-key error-radius soundness property, the
sticky-explore dispatch handshake, and the trace-count witness that the
cascade really compiles inside jit."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skyline_tpu.ops import device_cascade as dc
from skyline_tpu.ops.device_cascade import (
    cascade_trace_count,
    device_cascade_keep,
    device_cascade_mask,
)
from skyline_tpu.ops.dispatch import (
    choose_variant,
    device_cascade_mode,
    skyline_mask_auto,
)
from skyline_tpu.ops.dominance import skyline_mask
from skyline_tpu.ops.sorted_sfs import sorted_sfs_keep
from skyline_tpu.stream.batched import PartitionSet

# shared via conftest.py
from conftest import assert_same_merge, fill_pset, gen_points, merge_state

# ---------------------------------------------------------------------------
# mask-level parity: device cascade vs the quadratic referee
# ---------------------------------------------------------------------------


def _referee(x, valid=None):
    return np.asarray(skyline_mask(jnp.asarray(x), valid))


@pytest.mark.parametrize("kind", ["uniform", "correlated", "anti"])
@pytest.mark.parametrize("d", [2, 4, 8])
def test_mask_parity_grid(rng, kind, d):
    """Concrete AND jitted masks across the workload grid, with injected
    duplicates so the dedup path is always live."""
    x = gen_points(rng, 600, d, kind)
    x = np.concatenate([x, x[:37]])  # duplicates of real rows
    want = _referee(x)
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    assert np.array_equal(got, want), (kind, d, "concrete")
    jitted = np.asarray(jax.jit(device_cascade_mask)(jnp.asarray(x)))
    assert np.array_equal(jitted, want), (kind, d, "jit")


def test_mask_parity_with_valid(rng):
    x = gen_points(rng, 400, 4, "uniform")
    valid = rng.random(400) < 0.7
    got = np.asarray(device_cascade_mask(jnp.asarray(x), jnp.asarray(valid)))
    want = _referee(x, jnp.asarray(valid))
    assert np.array_equal(got, want)
    assert not got[~valid].any()


ADVERSARIAL = {
    "duplicates": np.repeat(
        np.array([[1, 9], [9, 1], [5, 5], [2, 8]], np.float32), 16, axis=0
    ),
    "zero-clump": np.concatenate([
        np.zeros((256, 4), np.float32),
        np.full((32, 4), 3.0, np.float32),
    ]),
    "equal-sums": np.array(
        [[0, 3], [1, 2], [2, 1], [3, 0], [1.5, 1.5]], np.float32
    ).repeat(8, axis=0),
    "nan-inf": np.array(
        [
            [1, 1, 1],
            [np.nan, 0, 0],
            [np.inf, np.inf, np.inf],
            [0, np.nan, np.nan],
            [2, 2, 2],
            [np.inf, 0, 0],
        ],
        np.float32,
    ),
    # mixed +/- inf rows have NaN row sums: lo/hi become -inf/+inf, so
    # their block is never band-skipped
    "mixed-inf": np.array(
        [
            [np.inf, -np.inf, 0],
            [-np.inf, np.inf, 0],
            [-np.inf, -np.inf, -np.inf],
            [0, 0, 0],
            [np.inf, -np.inf, 1],
        ],
        np.float32,
    ),
    "signed-zero": np.array(
        [[-0.0, 0.0], [0.0, -0.0], [0.0, 0.0], [1.0, 1.0]], np.float32
    ),
    "single": np.array([[4, 2, 7]], np.float32),
    "empty": np.zeros((0, 5), np.float32),
}


@pytest.mark.parametrize("case", sorted(ADVERSARIAL))
def test_mask_parity_adversarial(case):
    x = ADVERSARIAL[case]
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    want = _referee(x)
    assert np.array_equal(got, want), case
    # byte-for-byte on the selected rows (the -0.0 fold is selection-only)
    assert x[got].tobytes() == x[want].tobytes(), case


def test_valid_nan_rows_survive(rng):
    """All-NaN and partial-NaN valid rows are dominance-neutral and must
    survive — the `| inert_s` leg of the final mask."""
    x = gen_points(rng, 64, 3, "uniform")
    x[10] = np.nan
    x[20, 1] = np.nan
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    assert got[10] and got[20]
    assert np.array_equal(got, _referee(x))


# ---------------------------------------------------------------------------
# f32 sum-key error radius: soundness property + equal-key band adversary
# ---------------------------------------------------------------------------


def test_radius_bounds_f32_key_error(rng):
    """|f32 row-sum key − exact (f64) sum| ≤ r = (d−1)·2⁻²³·Σ|x| for every
    row — the certificate the band scan's lo/hi ranges ride on."""
    for d in (2, 4, 8):
        x = (gen_points(rng, 2048, d, "anti") - 0.5) * np.float32(1e6)
        key = np.asarray(jnp.sum(jnp.asarray(x), axis=1), np.float64)
        exact = np.sum(x.astype(np.float64), axis=1)
        radius = np.asarray(
            jnp.float32((d - 1) * 2.0 ** -23)
            * jnp.sum(jnp.abs(jnp.asarray(x)), axis=1),
            np.float64,
        )
        assert (np.abs(key - exact) <= radius).all(), d


def test_equal_key_multi_block_band(monkeypatch):
    """Every row shares the exact f32 sum key 2^24 while spanning several
    scan blocks (block=8): the sort key gives the scan nothing, the band
    condition fires across all block pairs, and identity must still hold.
    fl(2^24 + c) == 2^24 for c < 1, so the three trailing rows tie the
    key with strictly different exact sums — the radius must keep their
    blocks mutually ambiguous."""
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE_BLOCK", "8")
    base = 16777216.0  # 2^24
    rows = [(base - j, float(j)) for j in range(2, 22)]
    rows += [(base, 0.5), (base, 1.0), (base, 0.75)]
    x = np.array(rows, np.float32)
    key = np.asarray(jnp.sum(jnp.asarray(x), axis=1))
    assert (key == np.float32(base)).all()  # the whole input is one band
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    assert np.array_equal(got, _referee(x))
    assert got[20] and not got[21] and not got[22]


# ---------------------------------------------------------------------------
# union keep: the flush-path primitive
# ---------------------------------------------------------------------------


def test_keep_union_semantics(rng):
    for d in (3, 6):
        old = gen_points(rng, 200, d, "anti")
        old = old[_referee(old)]  # a real skyline prefix
        rows = gen_points(rng, 300, d, "uniform")
        keep = device_cascade_keep(rows, old)
        union = np.concatenate([old, rows])
        want = _referee(union)[old.shape[0]:]
        assert np.array_equal(keep, want), d
        assert np.array_equal(keep, sorted_sfs_keep(rows, old)), d


def test_keep_empty_old(rng):
    rows = gen_points(rng, 150, 4, "uniform")
    keep = device_cascade_keep(rows, np.empty((0, 4), np.float32))
    assert np.array_equal(
        keep, np.asarray(device_cascade_mask(jnp.asarray(rows)))
    )


def test_keep_duplicate_of_old_survives():
    old = np.array([[1, 1]], np.float32)
    rows = np.array([[1, 1], [2, 2]], np.float32)
    keep = device_cascade_keep(rows, old)
    assert keep[0] and not keep[1]


# ---------------------------------------------------------------------------
# engine-level byte identity through the flush + published merge digest
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["uniform", "correlated", "anti"])
@pytest.mark.parametrize("d", [2, 4, 8])
@pytest.mark.parametrize("policy", ["incremental", "lazy", "overlap"])
def test_engine_byte_identity(monkeypatch, kind, d, policy):
    """The knob must never change a published byte: global merge digest
    (count, survivor vector, point bytes) identical across off/on/auto.
    The sorted-SFS knob is pinned off so the matrix isolates the device
    cascade's own arbitration."""
    monkeypatch.setenv("SKYLINE_SORTED_SFS", "off")
    states = {}
    for mode in ("off", "on", "auto"):
        monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", mode)
        rng = np.random.default_rng(37)
        pset = PartitionSet(3, d, flush_policy=policy)
        fill_pset(pset, rng, gen_points(rng, 384, d, kind), 3)
        states[mode] = merge_state(pset)
    assert_same_merge(states["off"], states["on"], f"{kind}/{d}/{policy}")
    assert_same_merge(states["off"], states["auto"], f"{kind}/{d}/{policy}")


def test_engine_byte_identity_both_auto(monkeypatch):
    """Both cascades in auto: the live flush arbitration (host cascade +
    quadratic rounds on this backend; the device cascade only joins the
    row when the host cascade is out of play — see _choose_lazy_path)
    must still publish the same bytes as everything off."""
    states = {}
    for sorted_mode, dc_mode in (("off", "off"), ("auto", "auto")):
        monkeypatch.setenv("SKYLINE_SORTED_SFS", sorted_mode)
        monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", dc_mode)
        rng = np.random.default_rng(11)
        pset = PartitionSet(3, 6, flush_policy="lazy")
        fill_pset(pset, rng, gen_points(rng, 512, 6, "anti"), 3)
        states[(sorted_mode, dc_mode)] = merge_state(pset)
    assert_same_merge(
        states[("off", "off")], states[("auto", "auto")], "both-auto"
    )


def test_engine_flush_counter(monkeypatch):
    """Forced on, a lazy flush must actually take the cascade path
    (flush.device_cascade counter + the profiler signature)."""
    from skyline_tpu.telemetry import Telemetry

    monkeypatch.setenv("SKYLINE_SORTED_SFS", "off")
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "on")
    tel = Telemetry()
    rng = np.random.default_rng(5)
    pset = PartitionSet(2, 4, flush_policy="lazy", counters=tel.counters)
    fill_pset(pset, rng, gen_points(rng, 400, 4, "anti"), 2)
    counters = dict(tel.counters.snapshot())
    assert counters.get("flush.device_cascade", 0) > 0
    variants = {r["variant"] for r in pset._flush_prof.doc()["kernels"]}
    assert "flush_device_cascade" in variants


# ---------------------------------------------------------------------------
# dispatch gate: knob, forced-on identity, trace behavior, Pallas path
# ---------------------------------------------------------------------------


def test_mode_knob(monkeypatch):
    monkeypatch.delenv("SKYLINE_DEVICE_CASCADE", raising=False)
    assert device_cascade_mode() == "auto"
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "off")
    assert device_cascade_mode() == "off"
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "bogus")
    assert device_cascade_mode() == "auto"


def test_dispatch_forced_on_matches_off(monkeypatch, rng):
    x = jnp.asarray(gen_points(rng, 300, 5, "anti"))
    monkeypatch.setenv("SKYLINE_SORTED_SFS", "off")
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "off")
    off = np.asarray(skyline_mask_auto(x))
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "on")
    on = np.asarray(skyline_mask_auto(x))
    assert np.array_equal(off, on)


def test_traced_dispatch_forced_on(monkeypatch, rng):
    """Unlike the host cascade, dc=on holds INSIDE jit: the traced auto
    mask must route to the cascade and still match the referee."""
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE", "on")
    x = jnp.asarray(gen_points(rng, 200, 4, "uniform"))
    got = np.asarray(jax.jit(skyline_mask_auto)(x))
    assert np.array_equal(got, _referee(np.asarray(x)))


def test_trace_count_witness(rng):
    """Jitting the cascade over a fresh shape must bump the Python-side
    trace counter exactly at compile time — the LIVE-under-jit witness
    obs_smoke.sh leans on."""
    x = jnp.asarray(gen_points(rng, 97, 7, "uniform"))
    before = cascade_trace_count()
    first = np.asarray(jax.jit(device_cascade_mask)(x))
    after_compile = cascade_trace_count()
    assert after_compile > before
    again = np.asarray(jax.jit(device_cascade_mask)(x))
    assert cascade_trace_count() == after_compile  # cached: no retrace
    assert np.array_equal(first, again)


def test_pallas_interpret_parity(monkeypatch, rng):
    """SKYLINE_PALLAS_INTERPRET=1 drives the cascade's Pallas tile path
    (buffer chunks, full self-prune, band tiles) on CPU."""
    monkeypatch.setenv("SKYLINE_PALLAS_INTERPRET", "1")
    x = gen_points(rng, 300, 4, "anti")
    x = np.concatenate([x, x[:16]])
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    assert np.array_equal(got, _referee(x))


def test_mixed_precision_bit_exact(monkeypatch, rng):
    """The mp bf16 pre-drop only certifies a subset of true dominance:
    masks stay bit-identical with the margin pass on."""
    x = gen_points(rng, 500, 6, "anti")
    want = _referee(x)
    monkeypatch.setenv("SKYLINE_MIXED_PRECISION", "1")
    got = np.asarray(device_cascade_mask(jnp.asarray(x)))
    assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# sticky exploration: the claim handshake + chooser sequencing
# ---------------------------------------------------------------------------


def test_claim_explore_one_shot():
    from skyline_tpu.telemetry.profiler import KernelProfiler

    prof = KernelProfiler(backend="cpu")
    assert prof.claim_explore("v", 4, 100)
    assert not prof.claim_explore("v", 4, 100)  # claimed, not recorded
    assert prof.claim_explore("v", 4, 100_000)  # different N-bucket
    with prof.record("w", 4, 100):
        pass
    assert not prof.claim_explore("w", 4, 100)  # measured signatures too


def test_choose_variant_sticky_sequence():
    """The exact cold-path sequence the flush loop sees: explore a, then
    b, then fall back to candidates[0] instead of re-running a cold
    candidate; once data lands, measured EMAs decide."""
    from skyline_tpu.telemetry.profiler import KernelProfiler

    prof = KernelProfiler(backend="cpu")
    cands = ("a", "b")
    assert choose_variant(prof, cands, 4, 100) == "a"  # claims a
    assert choose_variant(prof, cands, 4, 100) == "b"  # a in flight: b
    assert choose_variant(prof, cands, 4, 100) == "a"  # all claimed
    with prof.record("a", 4, 100):
        pass
    assert choose_variant(prof, cands, 4, 100) == "a"  # only measured one
    with prof.record("b", 4, 100):
        pass
    best = min(
        ("a", "b"), key=lambda v: prof.ema_ms(v, 4, 100)
    )
    assert choose_variant(prof, cands, 4, 100) == best


def test_choose_variant_no_profiler():
    assert choose_variant(None, ("a", "b"), 4, 100) == "a"


# ---------------------------------------------------------------------------
# knob plumbing
# ---------------------------------------------------------------------------


def test_block_knob(monkeypatch):
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE_BLOCK", "100")
    assert dc.device_cascade_block() == 128  # rounded up to a power of two
    monkeypatch.setenv("SKYLINE_DEVICE_CASCADE_BLOCK", "3")
    assert dc.device_cascade_block() == 8  # floor
    monkeypatch.delenv("SKYLINE_DEVICE_CASCADE_BLOCK", raising=False)
    assert dc.device_cascade_block() == 2048


def test_block_knob_identity(monkeypatch, rng):
    """Identity must hold at every block size, including blocks larger
    than the padded input."""
    x = gen_points(rng, 200, 5, "anti")
    want = _referee(x)
    for blk in ("8", "64", "8192"):
        monkeypatch.setenv("SKYLINE_DEVICE_CASCADE_BLOCK", blk)
        got = np.asarray(device_cascade_mask(jnp.asarray(x)))
        assert np.array_equal(got, want), blk
