"""Perf-trajectory sentinel (ISSUE 13): rolling-baseline regression watch.

Fabricated artifact trajectories in a tmp dir: a healthy history exits 0,
an injected regression in the newest round exits 1, absolute rules
(audit divergence) trip without a baseline, unhealthy multichip rounds
trip, and backend mixing / unreadable rounds degrade to skips — one bad
artifact must never blind the watch.
"""

import json

from skyline_tpu.telemetry import sentinel


def _bench(path, r, value, backend="tpu", extra=None):
    doc = {"parsed": {"value": value, "backend": backend,
                      "p50_window_latency_ms": 1_000_000.0 / value}}
    if extra:
        doc["parsed"].update(extra)
    (path / f"BENCH_r{r:02d}.json").write_text(json.dumps(doc))


def _multichip(path, r, ok=True, skipped=False):
    (path / f"MULTICHIP_r{r:02d}.json").write_text(
        json.dumps({"n_devices": 4, "rc": 0 if ok else 1, "ok": ok,
                    "skipped": skipped, "tail": ""})
    )


def test_healthy_trajectory_exits_zero(tmp_path, capsys):
    for r, v in enumerate([100.0, 110.0, 105.0, 112.0], start=1):
        _bench(tmp_path, r, v)
    _multichip(tmp_path, 1)
    assert sentinel.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "sentinel: ok" in out


def test_slow_drift_regression_exits_one(tmp_path, capsys):
    # each round is within any pairwise gate, but the newest has lost 40%
    # against the rolling median — exactly the drift bench_compare misses
    for r, v in enumerate([100.0, 98.0, 101.0, 99.0, 60.0], start=1):
        _bench(tmp_path, r, v)
    assert sentinel.main(["--dir", str(tmp_path), "--threshold", "0.3"]) == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_absolute_rule_trips_without_baseline(tmp_path, capsys):
    _bench(tmp_path, 1, 100.0)
    _bench(tmp_path, 2, 101.0,
           extra={"audit": {"divergence_total": 1}})
    assert sentinel.main(["--dir", str(tmp_path)]) == 1
    assert "absolute" in capsys.readouterr().out


def test_backend_mismatch_is_not_a_regression(tmp_path):
    # a TPU outage (cpu-fallback round) must not read as a perf collapse
    for r, v in enumerate([5000.0, 5100.0, 5050.0], start=1):
        _bench(tmp_path, r, v, backend="tpu")
    _bench(tmp_path, 4, 80.0, backend="cpu-fallback")
    assert sentinel.main(["--dir", str(tmp_path)]) == 0


def test_unhealthy_multichip_round_exits_one(tmp_path):
    _bench(tmp_path, 1, 100.0)
    _bench(tmp_path, 2, 101.0)
    _multichip(tmp_path, 1, ok=True)
    _multichip(tmp_path, 2, ok=False)
    assert sentinel.main(["--dir", str(tmp_path)]) == 1


def test_unreadable_round_is_skipped_not_fatal(tmp_path, capsys):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"no_parsed": 1}))
    for r, v in enumerate([100.0, 102.0], start=3):
        _bench(tmp_path, r, v)
    assert sentinel.main(["--dir", str(tmp_path)]) == 0
    err = capsys.readouterr().err
    assert "skipping" in err


def test_empty_directory_is_ok(tmp_path):
    assert sentinel.main(["--dir", str(tmp_path)]) == 0


def test_custom_rules_file(tmp_path):
    for r, v in enumerate([100.0, 100.0, 100.0], start=1):
        _bench(tmp_path, r, v, extra={"custom": {"metric": 10.0 * r}})
    rules = tmp_path / "rules.json"
    rules.write_text(json.dumps([
        {"label": "custom.metric", "path": ["custom", "metric"],
         "higher_is_better": True},
    ]))
    # 30 vs median(10, 20) = 20: improving, ok
    assert sentinel.main(
        ["--dir", str(tmp_path), "--rules", str(rules)]
    ) == 0
    rules.write_text(json.dumps([
        {"label": "custom.metric", "path": ["custom", "metric"],
         "higher_is_better": False, "threshold": 0.2},
    ]))
    # same numbers, direction flipped: +100% vs baseline now regresses
    assert sentinel.main(
        ["--dir", str(tmp_path), "--rules", str(rules)]
    ) == 1


def test_usage_errors_exit_two(tmp_path):
    assert sentinel.main(["--dir", str(tmp_path), "--window", "0"]) == 2
    bad = tmp_path / "bad_rules.json"
    bad.write_text("[{\"nope\": 1}]")
    assert sentinel.main(["--dir", str(tmp_path), "--rules", str(bad)]) == 2
