"""Sharded streaming engine (skyline_tpu/distributed): byte-identity of
the two-level tournament against the single-device engine, chip-level
witness pruning, chip WAL barriers, and chip-crash replay equivalence.

The grid is the PR's acceptance bar: for every distribution shape x
dimensionality x chip count x flush policy, the sharded engine's
published skyline must be byte-identical (rows AND order) to the
single-device engine's — including after an injected chip crash plus
WAL replay, with the audit plane at full sample reporting zero
divergence.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from skyline_tpu.bridge import MemoryBus, SkylineWorker
from skyline_tpu.bridge.wire import format_trigger, format_tuple_line
from skyline_tpu.distributed import ShardedEngine, ShardedPartitionSet
from skyline_tpu.parallel.chips import chip_devices, chip_of
from skyline_tpu.resilience import ResilienceConfig
from skyline_tpu.resilience.chip_wal import (
    ChipWalPlane,
    discover_chips,
    read_chip_records,
    verify_chip_barriers,
)
from skyline_tpu.resilience.faults import (
    FaultPlan,
    active_plan,
    clear,
    install_plan,
)
from skyline_tpu.resilience.supervisor import Supervisor
from skyline_tpu.resilience.wal import WalReplayError
from skyline_tpu.stream import EngineConfig, SkylineEngine
from skyline_tpu.stream.batched import PartitionSet
from skyline_tpu.telemetry import Telemetry
from skyline_tpu.workload.generators import uniform

from conftest import assert_same_merge, gen_points, merge_state

P = 4  # divisible by every chip count in the grid


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    clear()
    yield
    clear()


def _feed_pset(pset, x: np.ndarray, chunk: int = 97) -> None:
    """Identical ingest sequence for both engines: deterministic routing,
    chunked adds, the engine's own flush cadence after every chunk — so
    a sharded/single pair sees byte-identical flush points."""
    n = x.shape[0]
    pids = np.arange(n) % P
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        for p in range(P):
            rows = np.ascontiguousarray(x[lo:hi][pids[lo:hi] == p])
            if rows.shape[0]:
                pset.add_batch(p, rows, max_id=hi, now_ms=0.0)
        pset.maybe_flush()
    pset.flush_all()


# --------------------------------------------------------------------------
# the acceptance grid: distribution x d x chips x flush policy
# --------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["uniform", "correlated", "anti"])
@pytest.mark.parametrize("d", [2, 4, 8])
def test_sharded_matches_single_device_grid(rng, kind, d):
    x = gen_points(rng, 450, d, kind)
    for policy in ("incremental", "lazy"):
        single = PartitionSet(P, d, buffer_size=64, flush_policy=policy)
        _feed_pset(single, x)
        base = merge_state(single)
        for chips in (1, 2, 4):
            sp = ShardedPartitionSet(
                P, d, 64, chips=chips, flush_policy=policy
            )
            _feed_pset(sp, x)
            assert_same_merge(
                base, merge_state(sp),
                ctx=f"kind={kind} d={d} chips={chips} policy={policy}",
            )


def test_sharded_incremental_merge_across_batches(rng):
    """Identity must hold at every intermediate query, not just the end
    state (flush cadence + facade merge cache both in play)."""
    d = 4
    x = gen_points(rng, 600, d, "uniform")
    single = PartitionSet(P, d, buffer_size=64)
    sp = ShardedPartitionSet(P, d, 64, chips=2)
    n = x.shape[0]
    pids = np.arange(n) % P
    for lo in range(0, n, 150):
        hi = min(lo + 150, n)
        for ps in (single, sp):
            for p in range(P):
                rows = np.ascontiguousarray(x[lo:hi][pids[lo:hi] == p])
                if rows.shape[0]:
                    ps.add_batch(p, rows, max_id=hi, now_ms=0.0)
            ps.flush_all()
        assert_same_merge(
            merge_state(single), merge_state(sp), ctx=f"after {hi} rows"
        )
    # a repeated query against unchanged state is a facade cache hit and
    # must return the same bytes
    again = merge_state(sp)
    assert_same_merge(merge_state(single), again, ctx="cache-hit query")
    assert sp.merge_cache_hits >= 1


# --------------------------------------------------------------------------
# chip-level witness pruning
# --------------------------------------------------------------------------


def test_chip_prune_fires_and_preserves_identity(rng):
    """Skewed routing: partition 0 (chip 0 when chips == P) receives a
    cluster near the origin while every other partition receives points
    in the dominated upper quadrant — chip 0's witness strictly dominates
    the other chips' min-corners, so whole chips skip the cross-chip
    merge."""
    d = 2
    x = rng.random((448, d)).astype(np.float32) * 0.4 + 0.55
    x[::P] = rng.random((112, d)).astype(np.float32) * 0.05 + 0.01
    single = PartitionSet(P, d, buffer_size=64)
    _feed_pset(single, x)
    sp = ShardedPartitionSet(P, d, 64, chips=4)
    _feed_pset(sp, x)
    assert_same_merge(merge_state(single), merge_state(sp), ctx="pruned")
    stats = sp.sharded_stats()
    assert stats["chips"] == 4
    assert stats["chips_pruned"] > 0
    assert 0.0 < stats["pruned_chip_fraction"] <= 0.75
    info = stats["last"]
    assert info is not None
    pruned_ids = {e["chip"] for e in info["pruned"]}
    assert pruned_ids
    for e in info["pruned"]:
        assert e["witness"] not in pruned_ids, "witness chain must end alive"
    assert len(info["per_chip"]) == 4
    assert len(info["survivors"]) >= 1
    assert not (set(info["survivors"]) & pruned_ids)


def test_chip_prune_knob_disables(rng, monkeypatch):
    monkeypatch.setenv("SKYLINE_CHIP_PRUNE", "0")
    d = 2
    x = rng.random((448, d)).astype(np.float32) * 0.4 + 0.55
    x[::P] = rng.random((112, d)).astype(np.float32) * 0.05 + 0.01
    single = PartitionSet(P, d, buffer_size=64)
    _feed_pset(single, x)
    sp = ShardedPartitionSet(P, d, 64, chips=4)
    _feed_pset(sp, x)
    assert_same_merge(merge_state(single), merge_state(sp), ctx="no-prune")
    assert sp.sharded_stats()["chips_pruned"] == 0


# --------------------------------------------------------------------------
# engine level: full query path, audit plane at full sample, EXPLAIN
# --------------------------------------------------------------------------


def _run_engine(engine, x, trigger=True):
    n = x.shape[0]
    ids = np.arange(n, dtype=np.int64)
    for lo in range(0, n, 128):
        hi = min(lo + 128, n)
        engine.process_records(ids[lo:hi], x[lo:hi])
    if trigger:
        engine.process_trigger("0,0")
    out = []
    for _ in range(200):
        out.extend(engine.poll_results())
        if out:
            break
    return out


def test_sharded_engine_end_to_end_with_full_audit(rng, monkeypatch):
    monkeypatch.setenv("SKYLINE_AUDIT_SAMPLE", "1.0")
    d = 4
    cfg = EngineConfig(parallelism=2, dims=d, buffer_size=64,
                       domain_max=1.0, emit_skyline_points=True)
    x = gen_points(rng, 500, d, "uniform")
    base = _run_engine(SkylineEngine(cfg, telemetry=Telemetry()), x)
    sharded_telem = Telemetry()
    eng = ShardedEngine(cfg, chips=2, telemetry=sharded_telem)
    # the audit plane shadow-verifies PUBLISHED snapshots; attach a store
    # so the sharded answer actually reaches the auditor
    from skyline_tpu.serve import SnapshotStore

    eng.attach_snapshots(SnapshotStore(history=4))
    got = _run_engine(eng, x)
    assert len(base) == len(got) == 1
    assert got[0]["skyline_size"] == base[0]["skyline_size"]
    np.testing.assert_array_equal(
        np.asarray(got[0]["skyline_points"], dtype=np.float32),
        np.asarray(base[0]["skyline_points"], dtype=np.float32),
    )
    stats = eng.stats()
    assert stats["sharded"]["chips"] == 2
    assert stats["sharded"]["merges"] >= 1
    # the audit plane runs the sharded answer against the host oracle at
    # full sample — distributed execution must not change a single byte
    assert stats["audit"]["checks_total"] >= 1
    assert stats["audit"]["divergence_total"] == 0


def test_sharded_explain_carries_chip_attribution(rng):
    from skyline_tpu.telemetry.explain import format_plan

    d = 2
    cfg = EngineConfig(parallelism=2, dims=d, buffer_size=64,
                       domain_max=1.0, emit_skyline_points=True)
    telem = Telemetry()
    eng = ShardedEngine(cfg, chips=4, telemetry=telem)
    _run_engine(eng, gen_points(rng, 450, d, "correlated"))
    doc = telem.explain.latest()
    assert doc is not None
    ch = doc.get("chips")
    assert ch is not None
    assert ch["chips"] == 4
    assert len(ch["per_chip"]) == 4
    assert len(ch["survivors"]) >= 1
    assert doc["merge"]["path"] == "sharded_tree"
    rendered = format_plan(doc)
    assert "chips n=4" in rendered
    for e in ch["pruned"]:
        assert f"chip {e['chip']} pruned by witness of chip" in rendered


# --------------------------------------------------------------------------
# checkpoint topology portability
# --------------------------------------------------------------------------


def test_checkpoint_roundtrips_across_topologies(rng, tmp_path):
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    d = 4
    cfg = EngineConfig(parallelism=2, dims=d, buffer_size=64,
                       domain_max=1.0, emit_skyline_points=True)
    x = gen_points(rng, 400, d, "uniform")
    eng = ShardedEngine(cfg, chips=2)
    _run_engine(eng, x, trigger=False)
    eng.pset.flush_all()
    base = merge_state(eng.pset)
    path = str(tmp_path / "ckpt.npz")
    save_engine(eng, path)
    # sharded checkpoint -> single-device engine
    flat = load_engine(path)
    assert isinstance(flat, SkylineEngine)
    assert not isinstance(flat, ShardedEngine)
    assert_same_merge(base, merge_state(flat.pset), ctx="sharded->flat")
    # sharded checkpoint -> different chip count
    wide = load_engine(path, mesh_chips=4)
    assert isinstance(wide, ShardedEngine)
    assert wide.mesh_chips == 4
    assert_same_merge(base, merge_state(wide.pset), ctx="sharded->4chips")
    assert flat.records_in == wide.records_in == eng.records_in


def test_checkpoint_roundtrips_across_cluster_topologies(rng, tmp_path):
    """ISSUE 16: a checkpoint taken under one host/chip layout restores
    into any other — including flat -> cluster and a cluster saved at
    hosts=2 restored at hosts=4 with a DIFFERENT per-host chip count —
    with a byte-identical next answer."""
    from skyline_tpu.cluster import ClusterEngine
    from skyline_tpu.utils.checkpoint import load_engine, save_engine

    d = 4
    cfg = EngineConfig(parallelism=2, dims=d, buffer_size=64,
                       domain_max=1.0, emit_skyline_points=True)
    x = gen_points(rng, 400, d, "uniform")
    eng = ClusterEngine(cfg, hosts=2, chips_per_host=2)
    _run_engine(eng, x, trigger=False)
    eng.pset.flush_all()
    base = merge_state(eng.pset)
    path = str(tmp_path / "ckpt.npz")
    save_engine(eng, path)
    # cluster checkpoint -> flat single-host engine
    flat = load_engine(path)
    assert not isinstance(flat, ClusterEngine)
    assert_same_merge(base, merge_state(flat.pset), ctx="cluster->flat")
    # cluster checkpoint -> more hosts, different per-host chip count
    wide = load_engine(path, cluster_hosts=4, mesh_chips=1)
    assert isinstance(wide, ClusterEngine)
    assert wide.cluster_hosts == 4 and wide.chips_per_host == 1
    assert_same_merge(base, merge_state(wide.pset), ctx="cluster->4hosts")
    assert flat.records_in == wide.records_in == eng.records_in
    # and the reverse direction: a FLAT checkpoint boots a cluster
    flat_path = str(tmp_path / "flat.npz")
    save_engine(flat, flat_path)
    clustered = load_engine(flat_path, cluster_hosts=2, mesh_chips=2)
    assert isinstance(clustered, ClusterEngine)
    assert_same_merge(
        base, merge_state(clustered.pset), ctx="flat->cluster"
    )


# --------------------------------------------------------------------------
# chip WAL plane
# --------------------------------------------------------------------------


def test_chip_wal_barrier_fanout_and_verify(tmp_path):
    d = str(tmp_path)
    plane = ChipWalPlane(d, chips=3, fsync="off")
    plane.note_flush(0, 10, "e0")
    plane.merge_barrier(1, "g1", ["a", "b", "c"], [5, 0, 2])
    plane.merge_barrier(2, "g2", ["a", "b", "c"], [5, 1, 2])
    plane.close()
    assert discover_chips(d) == 3
    v = verify_chip_barriers(d)
    assert v == {"chips": 3, "common_seq": 2, "epoch": "g2", "agree": True}
    recs = read_chip_records(d, 3)
    assert [r["type"] for r in recs[0]] == [
        "flush", "chip-barrier", "chip-barrier",
    ]
    assert all(r[-1]["seq"] == 2 for r in recs)


def test_chip_wal_torn_fanout_is_ignored(tmp_path):
    """A crash mid-fan-out leaves the barrier on SOME journals only; that
    seq is not common to all, so verification ignores it rather than
    reporting divergence."""
    d = str(tmp_path)
    plane = ChipWalPlane(d, chips=2, fsync="off")
    plane.merge_barrier(1, "g1", ["a", "b"], [1, 1])
    plane.close()
    # simulate a torn seq-2 fan-out: only chip 0's journal gets it
    torn = ChipWalPlane(d, chips=2, fsync="off")
    torn._writers[0].append({
        "type": "chip-barrier", "seq": 2, "chip": 0, "chips": 2,
        "epoch": "g2", "chip_epoch": "a2", "g": 1,
    })
    torn.close()
    v = verify_chip_barriers(d)
    assert v["common_seq"] == 1 and v["epoch"] == "g1" and v["agree"]


def test_chip_wal_divergence_raises(tmp_path):
    d = str(tmp_path)
    plane = ChipWalPlane(d, chips=2, fsync="off")
    plane._writers[0].append({
        "type": "chip-barrier", "seq": 1, "chip": 0, "chips": 2,
        "epoch": "gX", "chip_epoch": "a", "g": 1,
    })
    plane._writers[1].append({
        "type": "chip-barrier", "seq": 1, "chip": 1, "chips": 2,
        "epoch": "gY", "chip_epoch": "b", "g": 1,
    })
    plane.close()
    with pytest.raises(WalReplayError, match="divergence"):
        verify_chip_barriers(d)


def test_chip_wal_empty_layout_trivially_agrees(tmp_path):
    v = verify_chip_barriers(str(tmp_path))
    assert v == {"chips": 0, "common_seq": None, "epoch": None,
                 "agree": True}


# --------------------------------------------------------------------------
# chip-crash chaos: injected crash at the per-chip merge + WAL replay
# must reproduce the uninterrupted single-device answer byte-for-byte
# --------------------------------------------------------------------------


def _feed(bus, rows, start_id=0):
    bus.produce_many(
        "input-tuples",
        [format_tuple_line(start_id + i, row) for i, row in enumerate(rows)],
    )


def _sharded_worker(bus, tmp_path, d, chips, telem=None):
    res = ResilienceConfig(checkpoint_dir=str(tmp_path), wal_fsync="batch")
    return SkylineWorker(
        bus,
        EngineConfig(parallelism=2, dims=d, domain_max=10000.0,
                     buffer_size=128, emit_skyline_points=True),
        mesh_chips=chips,
        resilience=res,
        telemetry=telem,
    )


def _drive_to_result(worker, bus, out, shared, chunk=64):
    idle = 0
    while True:
        if worker.step(max_records=chunk):
            idle = 0
            continue
        if not shared["trigger_sent"]:
            bus.produce("queries", format_trigger(0, 0))
            shared["trigger_sent"] = True
            continue
        shared["lines"].extend(out.poll())
        if shared["lines"]:
            return json.loads(shared["lines"][-1])
        idle += 1
        assert idle < 500, "worker went idle without producing a result"


def _run_sharded_stream(tmp_path, rows, d, chips, plan_spec):
    bus = MemoryBus()
    _feed(bus, rows)
    out = bus.consumer("output-skyline", from_beginning=True)
    telem = Telemetry()
    shared = {"trigger_sent": False, "lines": []}
    holder = {}
    if plan_spec:
        install_plan(FaultPlan.parse(plan_spec))

    def incarnation(attempt):
        w = _sharded_worker(bus, tmp_path, d, chips, telem=telem)
        holder["w"] = w
        return _drive_to_result(w, bus, out, shared)

    sup = Supervisor(incarnation, max_restarts=8, backoff_base_s=0.0,
                     backoff_cap_s=0.0, telemetry=telem, sleep=lambda s: None)
    stats_doc = None
    try:
        doc = sup.run()
        stats_doc = holder["w"].stats()  # before close() drops the planes
    finally:
        clear()
        if holder.get("w") is not None:
            holder["w"].close()
    return doc, holder["w"], sup, stats_doc


@pytest.mark.parametrize("chips,plan", [
    (2, "crash@sharded.chip_merge:1"),
    (4, "crash@sharded.chip_merge:3,crash@kafka.poll:7"),
])
def test_chaos_chip_crash_replay_equals_single_device(rng, tmp_path, chips,
                                                      plan):
    n = 400
    d = 4
    rows = uniform(rng, n, d, 0, 10000)
    # the reference answer comes from an UNSHARDED uninterrupted worker:
    # equality across both the crash schedule and the topology
    base_bus = MemoryBus()
    _feed(base_bus, rows)
    base_out = base_bus.consumer("output-skyline", from_beginning=True)
    base_w = SkylineWorker(
        base_bus,
        EngineConfig(parallelism=2, dims=d, domain_max=10000.0,
                     buffer_size=128, emit_skyline_points=True),
    )
    base_doc = _drive_to_result(
        base_w, base_bus, base_out, {"trigger_sent": False, "lines": []}
    )
    base_w.close()

    doc, w, sup, stats = _run_sharded_stream(tmp_path, rows, d, chips, plan)
    assert sup.restarts >= 1, "the fault plan never fired"
    assert active_plan() is None
    assert w.engine.records_in == n
    assert doc["skyline_size"] == base_doc["skyline_size"]
    np.testing.assert_array_equal(
        np.asarray(doc["skyline_points"], dtype=np.float32),
        np.asarray(base_doc["skyline_points"], dtype=np.float32),
    )
    # the survivor's chip journals hold a consistent barrier history
    cw = stats["resilience"].get("chip_wal")
    assert cw is not None and cw["chips"] == chips
    assert cw["barriers_written"] >= 1
    v = verify_chip_barriers(w._wal_dir, chips)
    assert v["agree"] and v["common_seq"] is not None
    rec = w._recovered
    assert rec is not None and rec["wal_records"] > 0


# --------------------------------------------------------------------------
# construction + config validation
# --------------------------------------------------------------------------


def test_chip_devices_round_robin_and_ownership():
    devs = chip_devices(4)
    assert len(devs) == 4
    assert chip_of(0, 2) == 0 and chip_of(1, 2) == 0
    assert chip_of(2, 2) == 1 and chip_of(3, 2) == 1
    with pytest.raises(ValueError):
        chip_devices(0)


def test_sharded_pset_validates_divisibility():
    with pytest.raises(ValueError):
        ShardedPartitionSet(4, 2, 64, chips=3)
    with pytest.raises(ValueError):
        ShardedPartitionSet(4, 2, 64, chips=0)


def test_sharded_engine_rejects_device_ingest():
    with pytest.raises(ValueError, match="ingest"):
        ShardedEngine(
            EngineConfig(parallelism=2, dims=2, ingest="device"), chips=2
        )


def test_job_config_validates_mesh_chips():
    from skyline_tpu.utils.config import JobConfig

    assert JobConfig(parallelism=2, mesh_chips=2).mesh_chips == 2
    with pytest.raises(ValueError, match="mutually exclusive"):
        JobConfig(parallelism=2, mesh=2, mesh_chips=2)
    with pytest.raises(ValueError, match="divisible"):
        JobConfig(parallelism=2, mesh_chips=3)
    with pytest.raises(ValueError, match="mesh-chips|mesh_chips"):
        JobConfig(parallelism=2, mesh_chips=2, window_size=64, slide=32)
    with pytest.raises(ValueError):
        JobConfig(parallelism=2, mesh_chips=-1)


def test_worker_rejects_mesh_chips_with_window():
    with pytest.raises(ValueError):
        SkylineWorker(
            MemoryBus(),
            EngineConfig(parallelism=2, dims=2),
            mesh_chips=2,
            window_size=64,
            slide=32,
        )
