"""Foreign-implementation interop for the kafkalite wire protocol.

The golden-bytes tests (test_kafkalite_golden.py) pin frames against
spec-derived assemblies; these tests close the loop against a REAL foreign
implementation when one is available:

- kafka-python client <-> kafkalite Broker (same-process TCP)
- kafkalite client <-> external broker named by SKYLINE_INTEROP_BOOTSTRAP

Both skip cleanly when the dependency is absent — this image has no
kafka-python, no JVM, and no package egress (probe recorded in
``artifacts/kafka_interop.json`` by scripts/kafka_interop.py), so on the
build machine they skip; run them wherever kafka-python or a real broker
exists.
"""

import os

import pytest

kafka = pytest.importorskip(
    "kafka", reason="kafka-python not installed (see artifacts/kafka_interop.json)"
)


def test_kafka_python_roundtrip_against_kafkalite_broker():
    from skyline_tpu.bridge.kafkalite.broker import Broker

    with Broker() as b:
        host, _, port = b.address.partition(":")
        prod = kafka.KafkaProducer(
            bootstrap_servers=b.address,
            value_serializer=lambda s: s.encode("utf-8"),
            api_version=(0, 11),
        )
        msgs = [f"{i},{i * 10},{i * 7}" for i in range(5000)]
        for m in msgs:
            prod.send("interop", m)
        prod.flush()
        cons = kafka.KafkaConsumer(
            "interop",
            bootstrap_servers=b.address,
            auto_offset_reset="earliest",
            value_deserializer=lambda v: v.decode("utf-8"),
            consumer_timeout_ms=5000,
            api_version=(0, 11),
        )
        got = [r.value for r in cons]
        assert got == msgs


def test_kafkalite_client_against_external_broker():
    bootstrap = os.environ.get("SKYLINE_INTEROP_BOOTSTRAP")
    if not bootstrap:
        pytest.skip("set SKYLINE_INTEROP_BOOTSTRAP=host:port of a real broker")
    from skyline_tpu.bridge.kafkalite.client import (
        KafkaLiteConsumer,
        KafkaLiteProducer,
    )

    prod = KafkaLiteProducer(bootstrap)
    msgs = [f"interop-{i}" for i in range(2000)]
    prod.send_many("skyline-interop-test", msgs)
    prod.flush()
    cons = KafkaLiteConsumer(
        "skyline-interop-test", bootstrap, auto_offset_reset="earliest",
        check_crcs=True,
    )
    got, idle = [], 0
    while len(got) < len(msgs) and idle < 50:
        batch = cons.poll(4096)
        idle = 0 if batch else idle + 1
        got.extend(batch)
    # an external broker may hold earlier runs' records; ours must be the tail
    assert got[-len(msgs):] == msgs
